"""Fleet referee + release gate verdict engine (ISSUE 17), synthetic inputs.

Tier-1 throughout and deliberately fleet-free: every test here drives the
verdict engine on hand-built observatory dumps / manifests / BENCH round
files, pinning the exit-code matrix WITHOUT spawning a single node:

    referee:       pass 0 · no_data 1 · safety_violation 2 · slo_tripped 3
                   · partial 4
    release gate:  + perf_regression 5 · fleet_missing 6 · tier1_failed 7,
                   severity-ordered (a fork outranks everything)

The fleet soak tests that produce these inputs for real live in
tests/test_fleet_soak.py."""

import json
import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.tools import chain_observatory as obs
from tendermint_tpu.tools import fleet_referee as ref
from tendermint_tpu.tools import perf_ledger
from tendermint_tpu.tools import release_gate as gate

T0 = 1_700_000_000.0


# -- synthetic evidence builders ----------------------------------------------


def make_dump(label, heights, *, tripped=False, fork_at=None, terminals=None):
    """One synthetic observatory dump shaped like capture_node_dump's
    output: a timeline (waterfall raw material), an SLO snapshot, tx
    terminals, and the `chain` hash window the safety auditor reads."""
    recs = []
    hashes = {}
    for h in range(1, heights + 1):
        t = T0 + h
        recs.append(
            {
                "height": h,
                "proposals": [{"ts": t}],
                "steps": [
                    {"step": "PRECOMMIT", "ts": t + 0.05},
                    {"step": "COMMIT", "ts": t + 0.08},
                ],
                "commit": {"ts": t + 0.1, "round": 0},
                "propagation": {},
            }
        )
        hx = f"{h:064x}"
        if fork_at is not None and h == fork_at:
            hx = "f" * 64  # this node committed a DIFFERENT block here
        hashes[str(h)] = hx
    return {
        "observatory_dump": 1,
        "node_id": label,
        "moniker": label,
        "timeline": {"heights": recs, "propagation_peers": {}},
        "slo": {
            "enabled": True,
            "any_tripped": tripped,
            "objectives": {
                "consensus_commit_latency": {
                    "verdict": "TRIPPED" if tripped else "ok",
                    "tripped": tripped,
                    "trips_total": 1 if tripped else 0,
                    "breaches": 3 if tripped else 0,
                    "observations": heights,
                    "worst_s": 0.5,
                    "burn_rate": {},
                }
            },
        },
        "txtrace": {"enabled": True, "terminals": terminals or {}},
        "chain": {"base": 1, "height": heights, "hashes": hashes},
    }


def write_dumps(directory, dumps):
    os.makedirs(directory, exist_ok=True)
    for d in dumps:
        path = os.path.join(directory, f"{obs.DUMP_PREFIX}{d['node_id']}.json")
        with open(path, "w") as f:
            json.dump(d, f)


def write_manifest(directory, labels_roles, *, seed=7, live=None):
    doc = {
        "fleet_manifest": 1,
        "seed": seed,
        "fingerprint": "feedfacefeedface",
        "schedule_fingerprint": "deadbeefdeadbeef",
        "nodes": [
            {
                "index": i,
                "label": lbl,
                "role": role,
                "live": (lbl in live) if live is not None else True,
            }
            for i, (lbl, role) in enumerate(labels_roles)
        ],
    }
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, ref.MANIFEST_NAME), "w") as f:
        json.dump(doc, f)
    return doc


# -- the safety auditor --------------------------------------------------------


def test_safety_audit_clean():
    dumps = [make_dump(f"n{i}", 8) for i in range(4)]
    audit = ref.safety_audit(dumps)
    assert audit["nodes_audited"] == 4
    assert audit["heights_checked"] == 8
    assert audit["violations"] == []


def test_safety_audit_names_the_forked_height():
    dumps = [make_dump("good0", 8), make_dump("good1", 8),
             make_dump("evil", 8, fork_at=7)]
    audit = ref.safety_audit(dumps)
    assert len(audit["violations"]) == 1
    viol = audit["violations"][0]
    assert viol["height"] == 7
    assert viol["hashes"]["evil"] == "f" * 64
    assert viol["hashes"]["good0"] == f"{7:064x}"


def test_safety_audit_ignores_unshared_heights():
    # a node that is ahead of everyone is NOT a violation — only heights
    # two or more nodes share are comparable
    dumps = [make_dump("a", 4), make_dump("b", 9, fork_at=9)]
    audit = ref.safety_audit(dumps)
    assert audit["heights_checked"] == 4
    assert audit["violations"] == []


# -- verdicts + exit codes -----------------------------------------------------


def test_exit_code_matrix_is_pinned():
    assert ref.EXIT_CODES == {
        "pass": 0,
        "no_data": 1,
        "safety_violation": 2,
        "slo_tripped": 3,
        "partial": 4,
    }
    assert (gate.EXIT_PASS, gate.EXIT_SAFETY, gate.EXIT_SLO,
            gate.EXIT_PARTIAL, gate.EXIT_PERF, gate.EXIT_FLEET_MISSING,
            gate.EXIT_TIER1) == (0, 2, 3, 4, 5, 6, 7)
    # severity: worst first, fork on top
    assert gate.SEVERITY == (2, 3, 4, 5, 6, 7)


def test_verdict_pass():
    report = ref.build_report([make_dump(f"n{i}", 6) for i in range(3)])
    assert report["verdict"] == "pass"
    assert report["exit_code"] == 0
    assert report["safety"]["violations"] == []
    assert not report["coverage"]["partial"]


def test_verdict_no_data():
    report = ref.build_report([])
    assert report["verdict"] == "no_data"
    assert report["exit_code"] == 1


def test_verdict_slo_tripped():
    dumps = [make_dump("ok0", 6), make_dump("burny", 6, tripped=True)]
    report = ref.build_report(dumps)
    assert report["verdict"] == "slo_tripped"
    assert report["exit_code"] == 3


def test_safety_outranks_slo_and_partial():
    # a fork + a tripped SLO + a corrupt dump: the fork names the verdict
    dumps = [make_dump("good", 8, tripped=True),
             make_dump("evil", 8, fork_at=3),
             {"node_id": "corrupt", "load_error": "ValueError('bad json')"}]
    report = ref.build_report(dumps)
    assert report["verdict"] == "safety_violation"
    assert report["exit_code"] == 2
    assert report["safety"]["violations"][0]["height"] == 3
    # the lesser findings are still reported, not masked
    assert report["slo_any_tripped"] is True
    assert report["coverage"]["partial"] is True


def test_waterfall_covers_every_node():
    dumps = [make_dump(f"n{i}", 5) for i in range(4)]
    report = ref.build_report(dumps)
    wf = report["waterfall"]
    assert wf["heights_merged"] == 5
    assert set(wf["per_node"]) == {"n0", "n1", "n2", "n3"}
    assert all(c == 5 for c in wf["per_node"].values())
    assert wf["uncovered"] == []


def test_terminals_fold_fleet_wide():
    dumps = [
        make_dump("a", 4, terminals={"delivered": 5, "rejected": 1}),
        make_dump("b", 4, terminals={"delivered": 7}),
    ]
    report = ref.build_report(dumps)
    assert report["terminals"] == {"delivered": 12, "rejected": 1}


# -- coverage: corrupt dumps and manifest ghosts -------------------------------


def test_corrupt_dump_is_partial_not_a_crash(tmp_path):
    d = str(tmp_path)
    write_dumps(d, [make_dump("n0", 6), make_dump("n1", 6)])
    with open(os.path.join(d, f"{obs.DUMP_PREFIX}corrupt.json"), "w") as f:
        f.write("{not json at all")
    rc = ref.main(["--dumps", d, "--check"])
    assert rc == 4
    with open(os.path.join(d, "fleet_report.json")) as f:
        report = json.load(f)
    assert report["verdict"] == "partial"
    assert any("corrupt" in m for m in report["coverage"]["missing"])
    assert any("corrupt" in m for m in report["coverage"]["failed_dumps"])
    # the healthy nodes still merged
    assert report["coverage"]["merged"] == 2


def test_manifest_names_nodes_that_never_dumped():
    manifest = {
        "fleet_manifest": 1,
        "seed": 1,
        "nodes": [
            {"label": "n0", "role": "validator", "live": True},
            {"label": "ghost", "role": "full", "live": True},
            {"label": "dead", "role": "full", "live": False},
        ],
    }
    report = ref.build_report([make_dump("n0", 5)], manifest=manifest)
    assert report["verdict"] == "partial"
    assert report["coverage"]["never_dumped"] == ["ghost"]
    # a node the harness knows DIED is not expected to dump
    assert "dead" not in report["coverage"]["missing"]
    assert report["coverage"]["expected_live"] == 2


def test_role_slo_fold(tmp_path):
    d = str(tmp_path)
    dumps = [make_dump("val0", 6), make_dump("val1", 6, tripped=True),
             make_dump("edge0", 6)]
    write_dumps(d, dumps)
    write_manifest(d, [("val0", "validator"), ("val1", "validator"),
                       ("edge0", "light_edge")])
    report = ref.build_report(obs.load_dumps(d), manifest=ref.load_manifest(d))
    rs = report["role_slo"]
    assert rs["validator"]["nodes"] == 2
    assert rs["validator"]["tripped"] == 1
    assert rs["validator"]["verdict"] == "TRIPPED"
    assert rs["light_edge"]["verdict"] == "ok"
    assert report["roles"]["edge0"] == "light_edge"
    assert report["manifest"]["schedule_fingerprint"] == "deadbeefdeadbeef"


# -- CLI + markdown ------------------------------------------------------------


def test_cli_fork_exits_2_and_markdown_names_the_height(tmp_path, capsys):
    d = str(tmp_path)
    write_dumps(d, [make_dump("good0", 8), make_dump("good1", 8),
                    make_dump("evil", 8, fork_at=7)])
    rc = ref.main(["--dumps", d, "--check"])
    assert rc == 2
    out = capsys.readouterr().out
    assert "SAFETY VIOLATION at height 7" in out
    md = open(os.path.join(d, "fleet_report.md")).read()
    assert "SAFETY VIOLATION at height 7" in md
    assert "evil" in md


def test_cli_pass_exits_0_and_without_check_always_0(tmp_path):
    d = str(tmp_path)
    write_dumps(d, [make_dump("n0", 5), make_dump("n1", 5)])
    assert ref.main(["--dumps", d, "--check"]) == 0
    write_dumps(d, [make_dump("evil", 5, fork_at=2)])
    # without --check the CLI reports but exits 0 (report-only mode)
    assert ref.main(["--dumps", d]) == 0


def test_cli_empty_dir_is_no_data(tmp_path):
    assert ref.main(["--dumps", str(tmp_path), "--check"]) == 1


# -- release gate composition --------------------------------------------------


def _bench_round(path, value, *, fleet=None, extra=None):
    blob = {"metric": "verify_commit_10k_latency", "value": value,
            "unit": "ms", "extra": dict(extra or {})}
    blob["extra"]["verify_commit_10k"] = {"speedup_e2e": 1.0}
    if fleet is not None:
        blob["extra"]["fleet_soak"] = fleet
    with open(path, "w") as f:
        json.dump({"n": 1, "cmd": "bench", "rc": 0, "parsed": blob}, f)


def test_release_gate_all_pass(tmp_path):
    d = os.path.join(str(tmp_path), "obs")
    write_dumps(d, [make_dump("n0", 5), make_dump("n1", 5)])
    result = gate.evaluate(fleet_dumps=d, perf_root=str(tmp_path))
    assert result["exit_code"] == 0
    assert result["verdict"] == "pass"
    assert result["gates"]["fleet"]["status"] == "pass"
    # empty perf ledger is a pass (young repo), not a failure
    assert result["gates"]["perf"]["status"] == "no_rounds"
    assert result["gates"]["tier1"]["status"] == "skipped"
    # the gate wrote the referee report next to the dumps
    assert os.path.exists(os.path.join(d, "fleet_report.json"))


def test_release_gate_safety_violation(tmp_path):
    d = os.path.join(str(tmp_path), "obs")
    write_dumps(d, [make_dump("good", 6), make_dump("evil", 6, fork_at=4)])
    result = gate.evaluate(fleet_dumps=d, perf_root=str(tmp_path))
    assert result["exit_code"] == 2
    assert result["gates"]["fleet"]["detail"]["safety_violations"] == [4]


def test_release_gate_fleet_missing(tmp_path):
    # no dumps directory at all
    result = gate.evaluate(
        fleet_dumps=os.path.join(str(tmp_path), "nope"),
        perf_root=str(tmp_path),
    )
    assert result["exit_code"] == 6
    # an empty directory is equally missing evidence
    empty = os.path.join(str(tmp_path), "empty")
    os.makedirs(empty)
    result = gate.evaluate(fleet_dumps=empty, perf_root=str(tmp_path))
    assert result["exit_code"] == 6
    # ... but explicitly skipping the fleet gate is recorded, not failed
    result = gate.evaluate(skip_fleet=True, perf_root=str(tmp_path))
    assert result["exit_code"] == 0
    assert result["gates"]["fleet"]["status"] == "skipped"


def test_release_gate_perf_regression(tmp_path):
    root = str(tmp_path)
    _bench_round(os.path.join(root, "BENCH_r01.json"), 100.0)
    _bench_round(os.path.join(root, "BENCH_r02.json"), 200.0)  # 2x slower
    result = gate.evaluate(skip_fleet=True, perf_root=root, tolerance=0.25)
    assert result["exit_code"] == 5
    assert result["gates"]["perf"]["status"] == "regression"
    assert any("headline regression" in f
               for f in result["gates"]["perf"]["detail"])


def test_release_gate_fleet_gate_column_regression(tmp_path):
    # a failing referee verdict recorded in the newest BENCH round trips
    # the perf gate even when the live fleet gate is skipped
    root = str(tmp_path)
    _bench_round(os.path.join(root, "BENCH_r01.json"), 100.0,
                 fleet={"verdict": "pass", "heights": 20,
                        "safety_violations": 0})
    _bench_round(os.path.join(root, "BENCH_r02.json"), 101.0,
                 fleet={"verdict": "safety_violation", "heights": 21,
                        "safety_violations": 1})
    result = gate.evaluate(skip_fleet=True, perf_root=root)
    assert result["exit_code"] == 5
    assert any("fleet gate failed" in f
               for f in result["gates"]["perf"]["detail"])


def test_release_gate_tier1_failed(tmp_path):
    result = gate.evaluate(skip_fleet=True, perf_root=str(tmp_path),
                           tier1_cmd="exit 3")
    assert result["exit_code"] == 7
    assert result["gates"]["tier1"]["detail"]["rc"] == 3
    result = gate.evaluate(skip_fleet=True, perf_root=str(tmp_path),
                           tier1_cmd="true")
    assert result["exit_code"] == 0


def test_release_gate_severity_order(tmp_path):
    # fork in the fleet AND a perf regression: the fork (2) wins
    d = os.path.join(str(tmp_path), "obs")
    write_dumps(d, [make_dump("good", 6), make_dump("evil", 6, fork_at=2)])
    root = str(tmp_path)
    _bench_round(os.path.join(root, "BENCH_r01.json"), 100.0)
    _bench_round(os.path.join(root, "BENCH_r02.json"), 500.0)
    result = gate.evaluate(fleet_dumps=d, perf_root=root)
    assert result["gates"]["fleet"]["exit_code"] == 2
    assert result["gates"]["perf"]["exit_code"] == 5
    assert result["exit_code"] == 2


def test_release_gate_cli(tmp_path):
    d = os.path.join(str(tmp_path), "obs")
    write_dumps(d, [make_dump("n0", 5), make_dump("n1", 5)])
    out = os.path.join(str(tmp_path), "gate.json")
    rc = gate.main(["--fleet-dumps", d, "--root", str(tmp_path),
                    "--out", out, "--check"])
    assert rc == 0
    with open(out) as f:
        summary = json.load(f)
    assert summary["release_gate"] == 1
    assert summary["verdict"] == "pass"
    # fork through the CLI path
    write_dumps(d, [make_dump("evil", 5, fork_at=3)])
    rc = gate.main(["--fleet-dumps", d, "--root", str(tmp_path), "--check"])
    assert rc == 2


# -- perf ledger fleet-gate column ---------------------------------------------


def test_perf_ledger_fleet_gate_column(tmp_path):
    root = str(tmp_path)
    _bench_round(os.path.join(root, "BENCH_r01.json"), 100.0)  # no fleet run
    _bench_round(os.path.join(root, "BENCH_r02.json"), 99.0,
                 fleet={"verdict": "pass", "heights": 21,
                        "safety_violations": 0})
    ledger = perf_ledger.load_ledger(root)
    r1, r2 = ledger["bench"]
    assert r1["fleet_gate"] is None and r1["fleet_gate_missing"]
    assert r2["fleet_gate"] == {"verdict": "pass", "heights": 21,
                                "violations": 0}
    assert not r2["fleet_gate_missing"]
    assert ledger["fleet_gate_missing_rounds"] == ["BENCH_r01.json"]
    assert perf_ledger.check_regressions(ledger) == []
    md = perf_ledger.render_markdown(ledger)
    assert "fleet gate" in md          # the column exists
    assert "pass·21h·0v" in md         # the round that ran it
    assert "missing" in md             # the round that did not


def test_perf_ledger_fleet_gate_failure_blocks_check(tmp_path):
    root = str(tmp_path)
    _bench_round(os.path.join(root, "BENCH_r01.json"), 100.0,
                 fleet={"verdict": "slo_tripped", "heights": 20,
                        "safety_violations": 0})
    ledger = perf_ledger.load_ledger(root)
    failures = perf_ledger.check_regressions(ledger)
    assert len(failures) == 1
    assert "fleet gate failed" in failures[0]
    assert "slo_tripped" in failures[0]
    assert perf_ledger.main(["--root", root, "--check"]) == 2


# -- observatory fleet hardening -----------------------------------------------


def test_merge_marks_partial_coverage_explicitly():
    dumps = [make_dump("n0", 5),
             {"node_id": "broke", "load_error": "OSError('gone')"}]
    merged = obs.merge(dumps)
    cov = merged["coverage"]
    assert cov == {"expected": 2, "merged": 1, "missing": ["broke"],
                   "partial": True}
    # the failed node keeps a row naming its error
    rows = {n["node"]: n for n in merged["nodes"]}
    assert rows["broke"]["load_error"] == "OSError('gone')"
    md = obs.render_markdown(merged)
    assert "PARTIAL COVERAGE" in md
    assert "broke" in md


def test_merge_full_coverage_is_not_partial():
    merged = obs.merge([make_dump("n0", 5), make_dump("n1", 5)])
    assert merged["coverage"]["partial"] is False
    assert merged["coverage"]["missing"] == []


def test_merge_window_bounds_retained_heights():
    # 100 deep dumps merged with a 5-height window keep only window records
    dumps = [make_dump(f"n{i}", 100) for i in range(3)]
    merged = obs.merge(dumps, max_heights=5)
    assert len(merged["heights"]) == 5
    assert merged["heights"][0]["height"] == 96
    for n in merged["nodes"]:
        assert n["heights"] == 100  # reported depth is pre-window


def test_scrape_fleet_names_unreachable_nodes():
    import asyncio

    # nothing listens on these ports: every scrape must come back as a
    # named scrape_error row, never an exception or a dropped node
    urls = ["http://127.0.0.1:1", "http://127.0.0.1:2"]
    dumps = asyncio.run(obs.scrape_fleet(urls, timeout=2.0, concurrency=2))
    assert len(dumps) == 2
    for d in dumps:
        assert d.get("scrape_error")
    merged = obs.merge(dumps)
    assert merged["coverage"]["partial"] is True
    assert len(merged["coverage"]["missing"]) == 2
