"""bench.py's stall guards: the driver's end-of-round bench must emit its
one JSON line even when the device tunnel hangs uninterruptibly (observed
r5: jax.devices() blocked in C without servicing SIGALRM, indefinitely)."""

import contextlib
import io
import json
import os
import sys
import time

import pytest


def _bench():
    import bench

    return bench


def test_watchdog_fires_and_resets():
    bench = _bench()
    with pytest.raises(TimeoutError):
        with bench.watchdog(1):
            time.sleep(3)
    # alarm cleared: nothing fires after the context exits
    with bench.watchdog(1):
        pass
    time.sleep(1.2)


def test_guarded_main_passes_child_json_through(tmp_path, monkeypatch):
    bench = _bench()
    stub = tmp_path / "stub_bench.py"
    stub.write_text('print(\'{"metric": "stub", "value": 1, "unit": "ms", "vs_baseline": 2.0}\')\n')
    monkeypatch.setattr(bench, "__file__", str(stub))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    out = buf.getvalue()
    assert json.loads(out)["metric"] == "stub"
    assert out.count("\n") == 1


def test_guarded_main_emits_fallback_on_hung_child(tmp_path, monkeypatch):
    bench = _bench()
    stub = tmp_path / "hang_bench.py"
    stub.write_text("import time\ntime.sleep(600)\n")
    monkeypatch.setattr(bench, "__file__", str(stub))
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "1")
    monkeypatch.setenv("TMTPU_BENCH_HARD_MARGIN_S", "1")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["value"] == -1
    assert "deadline" in rep["extra"]["error"]


def test_guarded_main_salvages_json_printed_before_hang(tmp_path, monkeypatch):
    """A child that prints its complete result and THEN hangs in teardown
    (the tunnel client's threads) must have that result forwarded."""
    bench = _bench()
    stub = tmp_path / "hang_after_json.py"
    stub.write_text(
        'import sys, time\n'
        'print(\'{"metric": "late", "value": 7, "unit": "ms", "vs_baseline": 3.0}\', flush=True)\n'
        "time.sleep(600)\n"
    )
    monkeypatch.setattr(bench, "__file__", str(stub))
    # deadline must comfortably cover interpreter startup under load: the
    # stub prints immediately, so 8 s total is plenty and stays flake-free
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "4")
    monkeypatch.setenv("TMTPU_BENCH_HARD_MARGIN_S", "4")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["metric"] == "late" and rep["value"] == 7


def test_guarded_main_salvages_json_from_crashing_child(tmp_path, monkeypatch):
    """A child that prints the result then exits NONZERO (teardown crash)
    must still have the result forwarded, not replaced by the fallback."""
    bench = _bench()
    stub = tmp_path / "crash_after_json.py"
    stub.write_text(
        'import sys\n'
        'print(\'{"metric": "crashy", "value": 9, "unit": "ms", "vs_baseline": 1.5}\')\n'
        "sys.exit(134)\n"
    )
    monkeypatch.setattr(bench, "__file__", str(stub))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["metric"] == "crashy" and rep["value"] == 9


def test_help_documents_flight_recorder_breakdown():
    """Acceptance: the per-stage breakdown bench attaches to its JSON
    `extra` is documented in `bench.py --help`."""
    import subprocess

    p = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(_bench().__file__)),
        timeout=120,
    )
    assert p.returncode == 0
    assert "verify_stats" in p.stdout
    assert "device_health" in p.stdout
    assert "stage_seconds" in p.stdout


def test_flight_recorder_extra_present_in_results():
    """extra.verify_stats carries the per-stage breakdown after a CPU flush,
    and even the stall-fallback JSON includes it (so a -1 result still
    localises the failed stage)."""
    import contextlib
    import io

    bench = _bench()
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto.keys import gen_ed25519

    priv = gen_ed25519(b"\x54" * 32)
    msgs = [b"bench-extra-%d" % i for i in range(3)]
    sigs = [priv.sign(m) for m in msgs]
    assert B.verify_batch(
        [priv.pub_key().bytes()] * 3, msgs, sigs, backend="cpu"
    ).all()

    extra = bench._flight_recorder_extra()
    assert extra["verify_stats"]["totals"]["cpu/cpu"]["flushes"] >= 1
    assert "stage_seconds" in extra["verify_stats"]
    assert "last_flush" in extra["verify_stats"]
    assert "device_up" in extra["device_health"]

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_fallback("device initialization stalled (test)")
    rep = json.loads(buf.getvalue())
    assert rep["value"] == -1
    assert rep["extra"]["error"].startswith("device initialization stalled")
    assert "verify_stats" in rep["extra"]
    assert "device_health" in rep["extra"]


def test_guarded_main_emits_fallback_on_dead_child(tmp_path, monkeypatch):
    bench = _bench()
    stub = tmp_path / "dead_bench.py"
    stub.write_text("import sys\nsys.exit(3)\n")
    monkeypatch.setattr(bench, "__file__", str(stub))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["value"] == -1
    assert "rc=3" in rep["extra"]["error"]
