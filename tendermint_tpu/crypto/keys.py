"""Key interfaces and the host-side Ed25519 implementation.

Mirrors the reference's crypto.PubKey/PrivKey interfaces (reference:
crypto/crypto.go:22,30): addresses are the first 20 bytes of SHA-256 of the raw
public key bytes. Host-side sign/verify rides the `cryptography` package
(OpenSSL, constant-time); the batched TPU path lives in
tendermint_tpu.crypto.batch / tendermint_tpu.ops.ed25519_jax.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives import serialization

    _HAVE_OPENSSL = True
except ImportError:  # pragma: no cover - exercised only in minimal images
    # Gated fallback: containers without the `cryptography` wheel ride the
    # pure-Python RFC 8032 implementation (crypto/ed25519_ref.py) for host
    # sign/verify — slow (~ms/op) but exact for the COFACTORED predicate
    # (the referee IS ed25519_ref). Cofactorless mode loses OpenSSL's
    # ref10-exact edge-case acceptance set (non-canonical A) in this
    # fallback; the edge-vector suite (tests/test_ed25519_edge_vectors.py)
    # pins that set and only runs where OpenSSL is present.
    _HAVE_OPENSSL = False

from tendermint_tpu.crypto import tmhash

ED25519_KEY_TYPE = "ed25519"
SR25519_KEY_TYPE = "sr25519"
BLS12_381_KEY_TYPE = "bls12_381"

PUBKEY_SIZE = 32
PRIVKEY_SIZE = 32  # seed
SIGNATURE_SIZE = 64
BLS_PUBKEY_SIZE = 48  # compressed G1
BLS_SIGNATURE_SIZE = 96  # compressed G2
ADDRESS_SIZE = tmhash.TRUNCATED_SIZE


def address_from_pubkey_bytes(pubkey_bytes: bytes) -> bytes:
    return tmhash.sum_truncated(pubkey_bytes)


_P25519 = 2**255 - 19

# Framework-wide Ed25519 verification predicate. "cofactored" (default) is
# the ZIP-215-style predicate every device path implements natively;
# "cofactorless" is reference-exact (Go ed25519.Verify, reference:
# crypto/ed25519/ed25519.go) for mixed fleets that co-validate with
# reference nodes — cofactored accepts a strict superset (crafted
# small-torsion signatures), a consensus-fork vector at the 2/3 boundary.
# In cofactorless mode, DEFAULT-routed batch verification runs on the host
# (crypto/batch.backend_default); explicitly-requested device backends are
# honored and stay cofactored (tests/bench). Set via config
# (base.ed25519_verify_mode), TMTPU_ED25519_MODE, or set_verify_mode().
_VERIFY_MODE = os.environ.get("TMTPU_ED25519_MODE", "cofactored")
if _VERIFY_MODE not in ("cofactored", "cofactorless"):
    # Fail fast: a typo'd mode silently running the default would be the
    # exact consensus-fork hazard the flag exists to close.
    raise ValueError(
        f"TMTPU_ED25519_MODE={_VERIFY_MODE!r} is not 'cofactored' or 'cofactorless'"
    )


# True once the predicate has been CONSULTED (cofactorless_mode() is the
# single choke point every verification/routing site reads). Lets
# set_verify_mode surface the process-global last-writer-wins hazard:
# changing the mode after signatures were already judged under the old one
# (e.g. two in-process Nodes with differing configs) is silent otherwise.
_MODE_READ = False


def set_verify_mode(mode: str) -> None:
    global _VERIFY_MODE
    if mode not in ("cofactored", "cofactorless"):
        raise ValueError(f"unknown ed25519 verify mode {mode!r}")
    if mode != _VERIFY_MODE and _MODE_READ:
        import logging

        logging.getLogger("tendermint_tpu.crypto.keys").warning(
            "ed25519 verify mode changing %r -> %r after signatures were "
            "already verified under the old mode; the predicate is "
            "process-global, so ALL in-process nodes now use %r "
            "(last writer wins)",
            _VERIFY_MODE, mode, mode,
        )
    _VERIFY_MODE = mode


def cofactorless_mode() -> bool:
    global _MODE_READ
    _MODE_READ = True
    return _VERIFY_MODE == "cofactorless"


def _canonical_y(enc: bytes) -> bool:
    """True iff the 32-byte point encoding's y coordinate is canonical
    (< 2^255-19 after stripping the sign bit)."""
    return (int.from_bytes(enc, "little") & ((1 << 255) - 1)) < _P25519


class PubKey:
    """Public key interface: address(), bytes(), verify(), type_name()."""

    def address(self) -> bytes:
        raise NotImplementedError

    def bytes(self) -> bytes:
        raise NotImplementedError

    def verify(self, msg: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def type_name(self) -> str:
        raise NotImplementedError

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PubKey)
            and self.type_name() == other.type_name()
            and self.bytes() == other.bytes()
        )

    def __hash__(self) -> int:
        return hash((self.type_name(), self.bytes()))


class PrivKey:
    def bytes(self) -> bytes:
        raise NotImplementedError

    def sign(self, msg: bytes) -> bytes:
        raise NotImplementedError

    def pub_key(self) -> PubKey:
        raise NotImplementedError

    def type_name(self) -> str:
        raise NotImplementedError


@dataclass(frozen=True)
class Ed25519PubKey(PubKey):
    key_bytes: bytes

    def __post_init__(self):
        if len(self.key_bytes) != PUBKEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUBKEY_SIZE} bytes")

    def address(self) -> bytes:
        return address_from_pubkey_bytes(self.key_bytes)

    def bytes(self) -> bytes:
        return self.key_bytes

    def verify(self, msg: bytes, sig: bytes) -> bool:
        """Cofactored (ZIP-215-style) verification with canonical encodings
        — the framework's single verification predicate on every path (see
        crypto/ed25519_ref.verify_cofactored). Fast path: OpenSSL's
        cofactorless accept is a subset of cofactored accept, so an OpenSSL
        accept is final; an OpenSSL reject triggers the (rare) pure-Python
        cofactored recheck, which only differs on crafted small-torsion
        inputs. Canonical A/R encodings are required up front because the
        device kernels reject them (documented divergence from
        golang.org/x/crypto, which accepts non-canonical A).

        Cost bound: the referee is pure Python (~7 ms measured) vs ~0.2 ms
        for an OpenSSL reject — a ~30x amplification that fires ONLY on
        rejected signatures. Every reject path in the protocol punishes the
        sender (invalid vote -> peer ban, bad handshake -> connection drop,
        bad evidence -> rejected), so a flood of invalid signatures costs
        the attacker its connection after the first one; large hostile
        batches ride the device per-sig kernel, not this wrapper."""
        if len(sig) != SIGNATURE_SIZE:
            return False
        if not _HAVE_OPENSSL:
            from tendermint_tpu.crypto import ed25519_ref

            if cofactorless_mode():
                # pure-Python cofactorless (x/crypto's equation; NOT
                # ref10-exact on non-canonical A — see the import fallback)
                return ed25519_ref.verify(self.key_bytes, msg, sig)
            if not (_canonical_y(self.key_bytes) and _canonical_y(sig[:32])):
                return False
            return ed25519_ref.verify_cofactored(self.key_bytes, msg, sig)
        if cofactorless_mode():
            # Reference-exact: delegate ENTIRELY to OpenSSL, including the
            # canonical-encoding prechecks — OpenSSL's ref10-lineage
            # acceptance set matches the reference's golang.org/x/crypto
            # (non-canonical A accepted, non-canonical R rejected by the
            # R-encoding comparison, s < L enforced). Running our canonical
            # precheck here would itself be a divergence (we'd reject
            # non-canonical A that reference peers accept). Non-canonical
            # VALIDATOR keys are still blocked in both modes at ingestion
            # (pubkey_from_type_and_bytes).
            try:
                Ed25519PublicKey.from_public_bytes(self.key_bytes).verify(sig, msg)
                return True
            except (InvalidSignature, ValueError):
                return False
        if not (_canonical_y(self.key_bytes) and _canonical_y(sig[:32])):
            return False
        try:
            Ed25519PublicKey.from_public_bytes(self.key_bytes).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            pass
        from tendermint_tpu.crypto import ed25519_ref

        return ed25519_ref.verify_cofactored(self.key_bytes, msg, sig)

    def type_name(self) -> str:
        return ED25519_KEY_TYPE

    def __hash__(self) -> int:
        return hash((ED25519_KEY_TYPE, self.key_bytes))


@dataclass(frozen=True, repr=False)
class Ed25519PrivKey(PrivKey):
    seed: bytes

    def __repr__(self) -> str:  # never print private key material
        return "Ed25519PrivKey(<redacted>)"

    def __post_init__(self):
        if len(self.seed) != PRIVKEY_SIZE:
            raise ValueError(f"ed25519 privkey seed must be {PRIVKEY_SIZE} bytes")

    def bytes(self) -> bytes:
        return self.seed

    def sign(self, msg: bytes) -> bytes:
        if not _HAVE_OPENSSL:
            from tendermint_tpu.crypto import ed25519_ref

            return ed25519_ref.sign(self.seed, msg)
        return Ed25519PrivateKey.from_private_bytes(self.seed).sign(msg)

    def pub_key(self) -> Ed25519PubKey:
        if not _HAVE_OPENSSL:
            from tendermint_tpu.crypto import ed25519_ref

            return Ed25519PubKey(ed25519_ref.public_key(self.seed))
        pub = Ed25519PrivateKey.from_private_bytes(self.seed).public_key()
        raw = pub.public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        return Ed25519PubKey(raw)

    def type_name(self) -> str:
        return ED25519_KEY_TYPE


def gen_ed25519(seed: bytes | None = None) -> Ed25519PrivKey:
    return Ed25519PrivKey(seed if seed is not None else os.urandom(PRIVKEY_SIZE))


# ---------------------------------------------------------------------------
# BLS12-381 (aggregate-signature backend; crypto/bls_ref.py + ops/bls12_msm)


@dataclass(frozen=True)
class Bls12381PubKey(PubKey):
    """48-byte compressed G1 public key (minimal-pubkey-size ciphersuite).

    Subgroup membership is enforced at construction via the validator-
    ingestion gate (pubkey_from_type_and_bytes) — a non-subgroup key could
    make the aggregate pairing check and the per-signature fallback
    disagree, the exact per-node divergence the ed25519 canonicality gate
    exists to close."""

    key_bytes: bytes

    def __post_init__(self):
        if len(self.key_bytes) != BLS_PUBKEY_SIZE:
            raise ValueError(f"bls12_381 pubkey must be {BLS_PUBKEY_SIZE} bytes")

    def address(self) -> bytes:
        return address_from_pubkey_bytes(self.key_bytes)

    def bytes(self) -> bytes:
        return self.key_bytes

    def verify(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != BLS_SIGNATURE_SIZE:
            return False
        from tendermint_tpu.crypto import bls_ref

        return bls_ref.verify(self.key_bytes, msg, sig)

    def type_name(self) -> str:
        return BLS12_381_KEY_TYPE

    def __hash__(self) -> int:
        return hash((BLS12_381_KEY_TYPE, self.key_bytes))


@dataclass(frozen=True, repr=False)
class Bls12381PrivKey(PrivKey):
    seed: bytes  # >= 32-byte IKM for the spec KeyGen

    def __repr__(self) -> str:  # never print private key material
        return "Bls12381PrivKey(<redacted>)"

    def __post_init__(self):
        if len(self.seed) < 32:
            raise ValueError("bls12_381 privkey seed must be >= 32 bytes")

    @property
    def _sk(self) -> int:
        from tendermint_tpu.crypto import bls_ref

        return bls_ref.keygen(self.seed)

    def bytes(self) -> bytes:
        return self.seed

    def sign(self, msg: bytes) -> bytes:
        from tendermint_tpu.crypto import bls_ref

        return bls_ref.sign(self._sk, msg)

    def pub_key(self) -> Bls12381PubKey:
        from tendermint_tpu.crypto import bls_ref

        return Bls12381PubKey(bls_ref.sk_to_pk(self._sk))

    def pop_prove(self) -> bytes:
        """Proof of possession for rogue-key-safe aggregation."""
        from tendermint_tpu.crypto import bls_ref

        return bls_ref.pop_prove(self._sk)

    def type_name(self) -> str:
        return BLS12_381_KEY_TYPE


def gen_bls12_381(seed: bytes | None = None) -> Bls12381PrivKey:
    return Bls12381PrivKey(seed if seed is not None else os.urandom(32))


# Proof-of-possession registry: the rogue-key defense for aggregation.
# VerifyAggregateCommit refuses to fold any BLS key into an aggregate
# pairing check unless its PoP has been verified here (registration
# happens at validator ingestion: genesis doc / ABCI validator updates
# carry the proof next to the key). Per-signature verification does NOT
# require PoP — only aggregation is rogue-key-attackable. Process-global
# like the batch pipeline's pubkey cache.
_POP_VERIFIED: set = set()


def register_pop(pubkey_bytes: bytes, proof: bytes) -> bool:
    """Verify + record a proof of possession; False (not raised) on a bad
    proof so ingestion sites can reject the validator instead of dying."""
    from tendermint_tpu.crypto import bls_ref

    if bytes(pubkey_bytes) in _POP_VERIFIED:
        return True
    if not bls_ref.pop_verify(bytes(pubkey_bytes), bytes(proof)):
        return False
    _POP_VERIFIED.add(bytes(pubkey_bytes))
    return True


def pop_verified(pubkey_bytes: bytes) -> bool:
    return bytes(pubkey_bytes) in _POP_VERIFIED


def clear_pop_registry() -> None:
    """Test hook."""
    _POP_VERIFIED.clear()


def pubkey_from_type_and_bytes(type_name: str, data: bytes) -> PubKey:
    """Validator-ingestion entry point (genesis + ABCI validator updates).

    Rejects non-canonical ed25519 encodings (y >= p): the host backend
    (OpenSSL) accepts them while the TPU backend rejects them, so admitting
    such a key would let verification semantics diverge per-node — a fork
    risk. Enforcing canonicality here makes both backends agree for every key
    that can ever enter a validator set.
    """
    if type_name == ED25519_KEY_TYPE:
        if len(data) == PUBKEY_SIZE and not _canonical_y(data):
            raise ValueError("non-canonical ed25519 pubkey encoding (y >= p)")
        return Ed25519PubKey(data)
    if type_name == SR25519_KEY_TYPE:
        try:
            from tendermint_tpu.crypto.sr25519 import Sr25519PubKey
        except ImportError as e:  # pragma: no cover
            raise ValueError(f"sr25519 backend unavailable: {e}") from e
        return Sr25519PubKey(data)
    if type_name == BLS12_381_KEY_TYPE:
        from tendermint_tpu.crypto import bls_ref

        # Full decode: valid compressed encoding, on curve, IN SUBGROUP,
        # not the identity — anything less lets per-node verification
        # semantics diverge (see Bls12381PubKey docstring).
        pt = bls_ref.g1_from_bytes(data)
        if pt is None or bls_ref._jac_is_identity(pt):
            raise ValueError("invalid bls12_381 pubkey (encoding/subgroup)")
        return Bls12381PubKey(data)
    raise ValueError(f"unknown pubkey type {type_name!r}")
