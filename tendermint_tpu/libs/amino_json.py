"""Registered-type JSON: {"type": <registered name>, "value": ...} envelopes
for interface-typed values (reference: libs/json — amino-compatible JSON with
type tags; registrations like crypto/ed25519/ed25519.go:38-40).

Concrete types register an (name, encode, decode) triple; marshal/unmarshal
wrap/unwrap the envelope so heterogeneous values (e.g. PubKey variants)
round-trip through JSON without out-of-band type knowledge.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Callable, Dict, Tuple, Type

_BY_TYPE: Dict[Type, Tuple[str, Callable]] = {}
_BY_NAME: Dict[str, Callable] = {}


class UnregisteredTypeError(TypeError):
    pass


def register(cls: Type, name: str, encode: Callable[[Any], Any], decode: Callable[[Any], Any]) -> None:
    """reference: libs/json/registry.go RegisterType."""
    if name in _BY_NAME:
        raise ValueError(f"type name {name!r} already registered")
    _BY_TYPE[cls] = (name, encode)
    _BY_NAME[name] = decode


def marshal(value: Any) -> str:
    """Value -> '{"type": ..., "value": ...}' JSON."""
    for cls in type(value).__mro__:
        if cls in _BY_TYPE:
            name, encode = _BY_TYPE[cls]
            return json.dumps({"type": name, "value": encode(value)}, sort_keys=True)
    raise UnregisteredTypeError(f"{type(value).__name__} is not a registered type")


def unmarshal(data: str) -> Any:
    o = json.loads(data)
    if not isinstance(o, dict) or "type" not in o:
        raise ValueError("not a type-tagged JSON envelope")
    decode = _BY_NAME.get(o["type"])
    if decode is None:
        raise UnregisteredTypeError(f"unknown type tag {o['type']!r}")
    return decode(o.get("value"))


# -- standard registrations (reference tag names) ---------------------------


def _register_std() -> None:
    from tendermint_tpu.crypto.keys import Ed25519PrivKey, Ed25519PubKey

    register(
        Ed25519PubKey,
        "tendermint/PubKeyEd25519",
        lambda k: base64.b64encode(k.bytes()).decode(),
        lambda v: Ed25519PubKey(base64.b64decode(v)),
    )
    register(
        Ed25519PrivKey,
        "tendermint/PrivKeyEd25519",
        lambda k: base64.b64encode(k.bytes()).decode(),
        lambda v: Ed25519PrivKey(base64.b64decode(v)),
    )
    try:
        from tendermint_tpu.crypto.sr25519 import Sr25519PubKey

        register(
            Sr25519PubKey,
            "tendermint/PubKeySr25519",
            lambda k: base64.b64encode(k.bytes()).decode(),
            lambda v: Sr25519PubKey(base64.b64decode(v)),
        )
    except ImportError:  # sr25519 backend optional
        pass


_register_std()
