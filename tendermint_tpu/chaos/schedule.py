"""Deterministic fault schedules: a pure function of (seed, parameters).

A schedule is a flat, time-sorted list of FaultEvents. Generation draws every
decision from ONE `random.Random(seed)` stream, so the same seed always
produces the same schedule (Mersenne Twister sequences are stable across
Python versions for the operations used here); `fingerprint()` hashes the
canonical JSON so a soak log can prove which schedule ran, and
`to_json`/`from_json` round-trip a schedule into a post-mortem artifact.

Episodes are SEQUENTIAL (a partition heals before the next fault starts):
overlapping partitions+crashes can legitimately stall a 4-validator net for
their whole union, which turns a bounded soak into a timeout lottery. The
serialized form still interleaves start/end events ("partition" then "heal",
"crash" then "restart") so the engine replays a flat timeline.

Event kinds and their params:
  device_error  {"count": k}                 next k device calls raise
  device_hang   {"seconds": s}               next device call sleeps s
  partition     {"groups": [[...], [...]]}   split node indices into groups
  heal          {}                           clear partitions, re-dial mesh
  crash         {"target": i, "wal_fault": None|"truncate"|"corrupt"}
  restart       {"target": i}
  shard_error   {"shard": j}                 next sharded dispatch fails at shard j
  shard_hang    {"shard": j, "seconds": s}   next sharded dispatch straggles at shard j
  device_lost   {"device": j}                mesh device j dies (every dispatch
                                             including it fails, probes fail)
  device_revive {"device": j}                device j's probes pass again; the
                                             health model runs its rejoin cycle
  peer_stall    {"target": i, "seconds": s}  node i swallows block requests
  peer_lie      {"target": i, "count": k}    node i serves k commit-tampered blocks
  chunk_corrupt {"target": i, "count": k}    node i serves k bit-rotted snapshot chunks

The catchup-level kinds (ISSUE 12) fault the SERVING side of blocksync/
statesync via chaos/catchup.ServeFaults, so a rejoin soak's syncing nodes
meet stalling, lying, and corrupting peers on a reproducible timeline.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass
from typing import List, Sequence, Tuple

LEVEL_BY_KIND = {
    "device_error": "device",
    "device_hang": "device",
    "shard_error": "device",
    "shard_hang": "device",
    "device_lost": "device",
    "device_revive": "device",
    "partition": "network",
    "heal": "network",
    "crash": "process",
    "restart": "process",
    "peer_stall": "catchup",
    "peer_lie": "catchup",
    "chunk_corrupt": "catchup",
    "sig_poison": "adversary",
}


def _freeze(v):
    return tuple(_freeze(x) for x in v) if isinstance(v, (list, tuple)) else v


def _thaw(v):
    return [_thaw(x) for x in v] if isinstance(v, tuple) else v


@dataclass(frozen=True)
class FaultEvent:
    at: float  # seconds from schedule start
    kind: str  # see LEVEL_BY_KIND
    params: Tuple[Tuple[str, object], ...] = ()  # sorted key/value pairs

    @property
    def level(self) -> str:
        return LEVEL_BY_KIND[self.kind]

    def param_dict(self) -> dict:
        """Params with list values thawed back from tuples (the engine hands
        these to adapter methods as keyword arguments)."""
        return {k: _thaw(v) for k, v in self.params}

    @classmethod
    def make(cls, at: float, kind: str, **params) -> "FaultEvent":
        if kind not in LEVEL_BY_KIND:
            raise ValueError(f"unknown fault kind {kind!r}")
        frozen = tuple(sorted((k, _freeze(v)) for k, v in params.items()))
        return cls(round(float(at), 4), kind, frozen)


class ChaosSchedule:
    def __init__(self, seed: int, events: Sequence[FaultEvent]):
        self.seed = seed
        self.events: List[FaultEvent] = sorted(events, key=lambda e: (e.at, e.kind))

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ChaosSchedule)
            and self.seed == other.seed
            and self.events == other.events
        )

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def duration(self) -> float:
        return self.events[-1].at if self.events else 0.0

    # -- serialization ------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "events": [
                    {"at": e.at, "kind": e.kind, "params": {k: _thaw(v) for k, v in e.params}}
                    for e in self.events
                ],
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, text: str) -> "ChaosSchedule":
        o = json.loads(text)
        return cls(
            o["seed"],
            [
                FaultEvent.make(e["at"], e["kind"], **e.get("params", {}))
                for e in o["events"]
            ],
        )

    def fingerprint(self) -> str:
        """Stable hex digest of the canonical schedule — two runs with the
        same seed must log the same fingerprint (the reproducibility pin)."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:16]

    # -- generation ---------------------------------------------------------

    @classmethod
    def generate(
        cls,
        seed: int,
        n_nodes: int,
        *,
        episodes: int = 6,
        kinds: Sequence[str] = ("partition", "crash", "device_error", "device_hang"),
        min_gap: float = 1.0,
        max_gap: float = 3.0,
        min_episode: float = 2.0,
        max_episode: float = 5.0,
        protected: Sequence[int] = (),
        start_delay: float = 2.0,
        mesh_devices: int = 8,
    ) -> "ChaosSchedule":
        """Deterministic episode schedule. `protected` node indices are never
        crashed (e.g. the byzantine equivocator, whose misbehavior the soak
        must keep observing). Partitions isolate ONE node (3-1 style splits
        keep >2/3 power connected, so the net limps instead of halting)."""
        rng = random.Random(seed)
        crashable = [i for i in range(n_nodes) if i not in set(protected)]
        if "crash" in kinds and not crashable:
            raise ValueError(
                "no crashable nodes: every index is protected but 'crash' "
                "is a requested fault kind"
            )
        events: List[FaultEvent] = []
        t = start_delay + rng.uniform(0.0, max_gap - min_gap)
        for _ in range(max(0, int(episodes))):
            kind = rng.choice(list(kinds))
            if kind == "partition":
                lonely = rng.randrange(n_nodes)
                groups = [
                    [i for i in range(n_nodes) if i != lonely],
                    [lonely],
                ]
                dur = rng.uniform(min_episode, max_episode)
                events.append(FaultEvent.make(t, "partition", groups=groups))
                events.append(FaultEvent.make(t + dur, "heal"))
                t += dur
            elif kind == "crash":
                target = rng.choice(crashable)
                wal_fault = rng.choice([None, "truncate", "corrupt"])
                dur = rng.uniform(min_episode, max_episode)
                events.append(
                    FaultEvent.make(t, "crash", target=target, wal_fault=wal_fault)
                )
                events.append(FaultEvent.make(t + dur, "restart", target=target))
                t += dur
            elif kind == "device_error":
                events.append(
                    FaultEvent.make(t, "device_error", count=rng.randint(3, 6))
                )
            elif kind == "device_hang":
                events.append(
                    FaultEvent.make(
                        t, "device_hang", seconds=round(rng.uniform(0.05, 0.3), 3)
                    )
                )
            elif kind == "shard_error":
                events.append(
                    FaultEvent.make(
                        t, "shard_error", shard=rng.randrange(mesh_devices)
                    )
                )
            elif kind == "shard_hang":
                events.append(
                    FaultEvent.make(
                        t, "shard_hang", shard=rng.randrange(mesh_devices),
                        seconds=round(rng.uniform(0.05, 0.3), 3),
                    )
                )
            elif kind == "device_lost":
                device = rng.randrange(mesh_devices)
                dur = rng.uniform(min_episode, max_episode)
                events.append(FaultEvent.make(t, "device_lost", device=device))
                events.append(
                    FaultEvent.make(t + dur, "device_revive", device=device)
                )
                t += dur
            elif kind == "peer_stall":
                events.append(
                    FaultEvent.make(
                        t, "peer_stall", target=rng.randrange(n_nodes),
                        seconds=round(rng.uniform(min_episode, max_episode), 3),
                    )
                )
            elif kind == "peer_lie":
                events.append(
                    FaultEvent.make(
                        t, "peer_lie", target=rng.randrange(n_nodes),
                        count=rng.randint(1, 3),
                    )
                )
            elif kind == "chunk_corrupt":
                events.append(
                    FaultEvent.make(
                        t, "chunk_corrupt", target=rng.randrange(n_nodes),
                        count=rng.randint(1, 3),
                    )
                )
            elif kind == "sig_poison":
                # signature-poisoning flood: the target gossips votes whose
                # signatures pass precheck but fail real verification —
                # count must clear the scorer's quarantine (3) + punish (8)
                # gates so the defense pipeline runs end to end
                events.append(
                    FaultEvent.make(
                        t, "sig_poison", target=rng.randrange(n_nodes),
                        count=rng.randint(12, 20),
                    )
                )
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
            t += rng.uniform(min_gap, max_gap)
        return cls(seed, events)
