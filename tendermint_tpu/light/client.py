"""Light client: trust-minimized header tracking.

reference: light/client.go — NewClient (:113), initializeWithTrustOptions
(:292), VerifyLightBlockAtHeight (:415), verifySequential (:553),
verifySkipping (:643, bisection), backwards (:860), detectDivergence (:898
light/detector.go), replacePrimaryWithWitness (:1018).

All commit verification inside is batched over the validator axis (see
light/verifier.py) — a bisection over a 10k-validator chain is a handful of
device batches, not hundreds of thousands of serial verifies.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import List, Optional

from tendermint_tpu.light import verifier
from tendermint_tpu.light.provider import Provider, ProviderError
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    ErrNewValSetCantBeTrusted,
    LightError,
)
from tendermint_tpu.types.basic import NANOS
from tendermint_tpu.types.light import LightBlock
from tendermint_tpu.types.validator_set import Fraction

logger = logging.getLogger("tmtpu.light")

SEQUENTIAL = "sequential"
SKIPPING = "skipping"

DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * NANOS  # reference: light/client.go:40
DEFAULT_PRUNING_SIZE = 1000  # reference: light/client.go:36


class ErrConflictingHeaders(LightError):
    """A witness reported a different header for a verified height —
    possible attack (reference: light/errors.go ErrConflictingHeaders)."""

    def __init__(self, witness_index: int, height: int):
        self.witness_index = witness_index
        self.height = height
        self.conflicting_blocks: list = []
        super().__init__(f"witness #{witness_index} has a different header at height {height}")


class ErrNoWitnesses(LightError):
    """reference: light/errors.go errNoWitnesses."""


@dataclass
class TrustOptions:
    """Subjective initialization root (reference: light/trust_options.go)."""

    period_ns: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("negative or zero trusting period")
        if self.height <= 0:
            raise ValueError("negative or zero height")
        if len(self.hash) != 32:
            raise ValueError(f"expected hash size to be 32 bytes, got {len(self.hash)}")


def _now_ns() -> int:
    return time.time_ns()


class Client:
    """reference: light/client.go:113."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: List[Provider],
        trusted_store: LightStore,
        verification_mode: str = SKIPPING,
        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
    ):
        trust_options.validate()
        if verification_mode == SKIPPING:
            verifier.validate_trust_level(trust_level)
        elif verification_mode != SEQUENTIAL:
            raise ValueError(f"unknown verification mode {verification_mode!r}")
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses)
        # Conflicting headers retained after divergence detection, for
        # operator inspection / evidence submission (see
        # _compare_with_witnesses).
        self.conflicting_blocks: list = []
        self.store = trusted_store
        self.mode = verification_mode
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.pruning_size = pruning_size
        self._lock = asyncio.Lock()
        self._initialized = False

    # ------------------------------------------------------------- lifecycle

    async def initialize(self, now_ns: Optional[int] = None) -> LightBlock:
        """Fetch + pin the root of trust (reference: light/client.go:292
        initializeWithTrustOptions); checks the stored root against the trust
        options on restart (reference: checkTrustedHeaderUsingOptions :237)."""
        now_ns = now_ns if now_ns is not None else _now_ns()
        async with self._lock:
            existing = self.store.light_block(self.trust_options.height)
            if existing is not None and existing.hash() == self.trust_options.hash:
                self._initialized = True
                return existing
            lb = await self.primary.light_block(self.trust_options.height)
            if lb.hash() != self.trust_options.hash:
                raise LightError(
                    f"expected header's hash {self.trust_options.hash.hex()}, "
                    f"but got {lb.hash().hex()}"
                )
            lb.validate_basic(self.chain_id)
            if verifier.header_expired(lb.signed_header, self.trust_options.period_ns, now_ns):
                raise verifier.ErrOldHeaderExpired(
                    lb.time_ns + self.trust_options.period_ns, now_ns
                )
            # The commit must actually be signed by +2/3 of its own valset.
            lb.validator_set.verify_commit_light(
                self.chain_id, lb.signed_header.commit.block_id, lb.height,
                lb.signed_header.commit,
            )
            await self._compare_with_witnesses(lb)
            self.store.save_light_block(lb)
            self._initialized = True
            return lb

    async def _ensure_initialized(self, now_ns: int) -> None:
        if not self._initialized:
            raise LightError("client not initialized — call initialize() first")

    # ------------------------------------------------------------ public API

    async def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    async def update(self, now_ns: Optional[int] = None) -> Optional[LightBlock]:
        """Verify the latest header from primary
        (reference: light/client.go:465 Update)."""
        now_ns = now_ns if now_ns is not None else _now_ns()
        latest = await self._fetch_from_primary(None)
        last = self.store.latest_light_block()
        if last is not None and latest.height <= last.height:
            return None
        return await self.verify_light_block(latest, now_ns)

    async def verify_light_block_at_height(
        self, height: int, now_ns: Optional[int] = None
    ) -> LightBlock:
        """reference: light/client.go:415 VerifyLightBlockAtHeight."""
        if height <= 0:
            raise ValueError("height must be positive")
        now_ns = now_ns if now_ns is not None else _now_ns()
        await self._ensure_initialized(now_ns)
        existing = self.store.light_block(height)
        if existing is not None:
            return existing
        lb = await self._fetch_from_primary(height)
        return await self.verify_light_block(lb, now_ns)

    async def verify_light_block(self, new_lb: LightBlock, now_ns: int) -> LightBlock:
        """Verify a light block obtained elsewhere
        (reference: light/client.go:497 VerifyHeader)."""
        await self._ensure_initialized(now_ns)
        async with self._lock:
            existing = self.store.light_block(new_lb.height)
            if existing is not None:
                if existing.hash() != new_lb.hash():
                    raise LightError(
                        f"existing trusted header {existing.hash().hex()} does not "
                        f"match new one {new_lb.hash().hex()} at height {new_lb.height}"
                    )
                return existing
            new_lb.validate_basic(self.chain_id)

            first = self.store.first_light_block()
            if first is not None and new_lb.height < first.height:
                await self._backwards(first, new_lb, now_ns)
            else:
                closest = self.store.light_block_before(new_lb.height + 1)
                if closest is None:
                    raise LightError("no trusted state to verify from")
                if self.mode == SEQUENTIAL:
                    await self._verify_sequential(closest, new_lb, now_ns)
                else:
                    await self._verify_skipping(closest, new_lb, now_ns)

            await self._compare_with_witnesses(new_lb)
            self.store.save_light_block(new_lb)
            self.store.prune(self.pruning_size)
            return new_lb

    # -------------------------------------------------------- verify drivers

    async def _verify_sequential(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> None:
        """Verify every height between trusted and target
        (reference: light/client.go:553 verifySequential)."""
        current = trusted
        for h in range(trusted.height + 1, target.height + 1):
            inter = target if h == target.height else await self._fetch_from_primary(h)
            verifier.verify_adjacent(
                self.chain_id,
                current.signed_header,
                inter.signed_header,
                inter.validator_set,
                self.trust_options.period_ns,
                now_ns,
                self.max_clock_drift_ns,
            )
            if h != target.height:
                self.store.save_light_block(inter)
            current = inter

    async def _verify_skipping(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> None:
        """Bisection (reference: light/client.go:643 verifySkipping): try a
        non-adjacent jump; when the trusted valset can't vouch (+1/3 overlap
        missing), bisect to the midpoint and retry."""
        current = trusted
        to_verify = [target]
        while to_verify:
            candidate = to_verify[-1]
            try:
                if candidate.height == current.height + 1:
                    verifier.verify_adjacent(
                        self.chain_id,
                        current.signed_header,
                        candidate.signed_header,
                        candidate.validator_set,
                        self.trust_options.period_ns,
                        now_ns,
                        self.max_clock_drift_ns,
                    )
                else:
                    verifier.verify_non_adjacent(
                        self.chain_id,
                        current.signed_header,
                        current.validator_set,
                        candidate.signed_header,
                        candidate.validator_set,
                        self.trust_options.period_ns,
                        now_ns,
                        self.max_clock_drift_ns,
                        self.trust_level,
                    )
            except ErrNewValSetCantBeTrusted:
                pivot = (current.height + candidate.height) // 2
                if pivot in (current.height, candidate.height):
                    raise LightError(
                        f"bisection stuck between heights {current.height} and "
                        f"{candidate.height}"
                    )
                mid = await self._fetch_from_primary(pivot)
                if mid.height != pivot:
                    raise LightError(
                        f"primary returned height {mid.height} for requested "
                        f"pivot {pivot}"
                    )
                to_verify.append(mid)
                continue
            # verified
            to_verify.pop()
            if candidate.height != target.height:
                self.store.save_light_block(candidate)
            current = candidate

    async def _backwards(
        self, first_trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> None:
        """Walk the hash chain down from the first trusted header
        (reference: light/client.go:860 backwards)."""
        trusted = first_trusted
        for h in range(first_trusted.height - 1, target.height - 1, -1):
            inter = target if h == target.height else await self._fetch_from_primary(h)
            # validate_basic pins the block's valset to header.ValidatorsHash and
            # the commit to the header hash — without it a primary could attach
            # an attacker valset to a genuine header and poison the store.
            inter.validate_basic(self.chain_id)
            verifier.verify_backwards(
                self.chain_id, inter.signed_header, trusted.signed_header
            )
            if h != target.height:
                self.store.save_light_block(inter)
            trusted = inter

    # ------------------------------------------------------------- witnesses

    async def _compare_with_witnesses(self, lb: LightBlock) -> None:
        """Cross-check a verified header against all witnesses; a conflicting
        witness means a possible attack (reference: light/detector.go:33
        detectDivergence). Witnesses that don't respond are skipped; witnesses
        that conflict are removed and the error surfaced."""
        if not self.witnesses:
            return
        conflicts = []
        for i, w in enumerate(list(self.witnesses)):
            try:
                other = await w.light_block(lb.height)
            except ProviderError:
                continue
            if other.hash() != lb.hash():
                conflicts.append((i, w, other))
        if conflicts:
            # Keep the conflicting evidence available for operator
            # inspection (the reference builds LightClientAttackEvidence and
            # reports it to the honest providers, light/detector.go:116; we
            # record the diverging headers and surface them on the error).
            for i, w, other in conflicts:
                logger.error(
                    "witness %s reports conflicting header at height %d: "
                    "primary hash %s vs witness hash %s — possible light-client attack",
                    w,
                    lb.height,
                    lb.hash().hex(),
                    other.hash().hex(),
                )
                self.conflicting_blocks.append(other)
            for _, w, _other in conflicts:
                self.witnesses.remove(w)
            err = ErrConflictingHeaders(conflicts[0][0], lb.height)
            err.conflicting_blocks = [c[2] for c in conflicts]
            raise err

    async def _fetch_from_primary(self, height: Optional[int]) -> LightBlock:
        """Fetch from primary, replacing it with a witness on failure
        (reference: light/client.go:1004 lightBlockFromPrimary +
        :1018 replacePrimaryWithWitness)."""
        try:
            return await self.primary.light_block(height)
        except ProviderError as e:
            logger.warning("primary %s failed (%s); trying witnesses", self.primary, e)
            while self.witnesses:
                w = self.witnesses[0]
                try:
                    lb = await w.light_block(height)
                except ProviderError:
                    self.witnesses.pop(0)
                    continue
                # promote witness to primary; demote old primary to witness
                self.witnesses.pop(0)
                self.witnesses.append(self.primary)
                self.primary = w
                return lb
            raise ErrNoWitnesses(f"primary failed and no witness responded: {e}") from e

    # -------------------------------------------------------------- cleanup

    def first_trusted_height(self) -> Optional[int]:
        lb = self.store.first_light_block()
        return lb.height if lb else None

    def last_trusted_height(self) -> Optional[int]:
        lb = self.store.latest_light_block()
        return lb.height if lb else None
