"""Batched ristretto255 decode (ops/ristretto_jax.py) — differential vs the
host implementation (crypto/sr25519.py), including invalid and edge
encodings. Reference semantics: crypto/sr25519/pubkey.go:34 (go-schnorrkel
-> ristretto255 decode)."""

import pytest

pytestmark = [pytest.mark.kernel, pytest.mark.slow]  # heavy one-time
# compiles: excluded from the tier-1 budget lane (-m 'not slow'); run
# explicitly via -m kernel

import numpy as np

from tendermint_tpu.crypto.ed25519_ref import BASE, P, point_mul
from tendermint_tpu.crypto.sr25519 import ristretto_decode as host_decode
from tendermint_tpu.crypto.sr25519 import ristretto_encode
from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops.ristretto_jax import decode_rows


def _limbs_to_int(l):
    v = 0
    for i in range(fe.NLIMBS - 1, -1, -1):
        v = (v << fe.RADIX) + int(l[i])
    return v % P


def test_decode_matches_host():
    rng = np.random.default_rng(7)
    rows, expect = [], []
    for _ in range(20):  # valid: random multiples of the basepoint
        enc = ristretto_encode(point_mul(int(rng.integers(1, 1 << 60)), BASE))
        rows.append(np.frombuffer(enc, dtype=np.uint8))
        expect.append(host_decode(enc))
    for b in [
        b"\x01" + b"\x00" * 31,  # negative (odd) s
        b"\xff" * 32,  # non-canonical, high bit set
        bytes(32),  # identity encoding (valid)
        (P - 1).to_bytes(32, "little"),  # canonical field element, not a point
        P.to_bytes(32, "little"),  # non-canonical (== p)
        (2).to_bytes(32, "little"),
    ]:
        rows.append(np.frombuffer(b, dtype=np.uint8))
        expect.append(host_decode(b))
    coords, ok = decode_rows(np.stack(rows))
    for j, e in enumerate(expect):
        if e is None:
            assert not ok[j], f"lane {j} should be invalid"
            continue
        assert ok[j], f"lane {j} should be valid"
        x, y, z, t = (_limbs_to_int(coords[c][:, j]) for c in range(4))
        zinv = pow(z, P - 2, P)
        ex = e[0] * pow(e[2], P - 2, P) % P
        ey = e[1] * pow(e[2], P - 2, P) % P
        assert (x * zinv % P, y * zinv % P) == (ex, ey), f"lane {j} affine mismatch"
        assert t * zinv % P == (x * zinv % P) * (y * zinv % P) % P, f"lane {j} T"
