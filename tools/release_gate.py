#!/usr/bin/env python
"""Standalone runner for the release gate (ISSUE 17).

One entrypoint composing the fleet referee, the perf ledger's --check, and
optionally the tier-1 suite into a single severity-ordered exit code
(0 pass, 2 safety, 3 SLO, 4 partial coverage, 5 perf regression, 6 fleet
evidence missing, 7 tier-1 failed). Implementation:
tendermint_tpu/tools/release_gate.py. Usage:

    python tools/release_gate.py --fleet-dumps ./observatory --root . --check
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from tendermint_tpu.tools.release_gate import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
