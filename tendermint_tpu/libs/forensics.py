"""Stall forensics: phase heartbeats + a post-mortem capture for wedged flushes.

Every MULTICHIP round to date died opaquely — rc-124 timeouts and AOT
mismatches with no record of WHICH device phase wedged — and BENCH_r05 lost
its whole datapoint to a "device initialization stalled" with zero stage
attribution. The failure mode is always the same shape: a device entry point
(submit, finish/sync, probe) blocks in C and never returns, so in-process
watchdogs that rely on the wedged thread (SIGALRM handlers, deadline checks
on the flush path itself) never run either.

This module attacks that with two pieces that DON'T depend on the wedged
thread:

1. **Heartbeat** — a tiny mmap'd ring file. Each device entry point
   (`crypto/batch._device_fault`, which the chaos hook already enumerates:
   rlc_submit / rlc_finish / persig / probe — plus the sharded mesh entry
   points) stamps `(seq, monotonic, wall, pid, phase)` into the ring BEFORE
   touching the device. When the process wedges, the newest stamp names the
   phase it wedged in; because the file is mmap'd, an outside reader (the
   bench parent, an operator, a post-mortem) reads it even while — or after —
   the writer hangs. Overhead contract: with no heartbeat configured the
   hot-path `beat()` is one module-global None check.

2. **Watchdog + capture** — a daemon thread armed with a deadline. If not
   cancelled in time it calls `capture()`, which assembles a
   `FORENSICS_<stamp>_<pid>.json`: the wedged phase (newest heartbeat), the
   heartbeat tail, every thread's stack (faulthandler, readable even when the
   main thread is stuck in C), the verify-path circuit-breaker snapshot,
   device health from the flight recorder, a bounded-time `jax.devices()`
   probe (its own hang IS the diagnosis), and the machine fingerprint.
   bench.py arms one per scenario child so a hard hang yields a diagnosis
   file before the parent's process-group SIGKILL; `install_signal_handler`
   additionally lets the parent request a dump with SIGUSR1.

File format (`Heartbeat`): 16-byte header `TMHB1\\0 | u16 slots | u64 next
seq`, then `slots` fixed 64-byte records `u64 seq | f64 monotonic | f64
wall | u32 pid | 36s phase`. Readers sort by seq and ignore empty slots, so
a torn in-flight write costs at most one beat.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional

_MAGIC = b"TMHB1\x00"
_HEADER = struct.Struct("<6sHQ")  # magic, slot count, next seq
_RECORD = struct.Struct("<QddI36s")  # seq, monotonic, wall, pid, phase
SLOT_SIZE = 64
assert _RECORD.size <= SLOT_SIZE
DEFAULT_SLOTS = 64


class Heartbeat:
    """Writer half: stamp phases into the mmap'd ring. One instance per
    process (module-global via `configure`); thread-safe."""

    def __init__(self, path: str, slots: int = DEFAULT_SLOTS):
        self.path = path
        self.slots = max(1, int(slots))
        self._lock = threading.Lock()
        self._seq = 0
        size = _HEADER.size + self.slots * SLOT_SIZE
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # O_CREAT without O_TRUNC: re-opening an existing file continues its
        # sequence (a restarted process appends history instead of erasing
        # the pre-crash tail an investigator may still want)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            if os.fstat(fd).st_size < size:
                os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, slots_on_disk, seq = _HEADER.unpack_from(self._mm, 0)
        if magic == _MAGIC and slots_on_disk == self.slots:
            self._seq = seq
        else:
            _HEADER.pack_into(self._mm, 0, _MAGIC, self.slots, 0)

    def beat(self, phase: str) -> None:
        b = phase.encode()[:36]
        now_m, now_w = time.monotonic(), time.time()
        with self._lock:
            self._seq += 1
            slot = (self._seq - 1) % self.slots
            _RECORD.pack_into(
                self._mm,
                _HEADER.size + slot * SLOT_SIZE,
                self._seq, now_m, now_w, os.getpid(), b,
            )
            _HEADER.pack_into(self._mm, 0, _MAGIC, self.slots, self._seq)

    def close(self) -> None:
        try:
            self._mm.close()
        except Exception:
            pass

    @staticmethod
    def read(path: str, limit: Optional[int] = None) -> List[dict]:
        """Reader half: beats oldest-first (the newest names the wedged
        phase). Safe against a concurrently-writing — or hung — writer."""
        with open(path, "rb") as f:
            buf = f.read()
        if len(buf) < _HEADER.size:
            return []
        magic, slots, _seq = _HEADER.unpack_from(buf, 0)
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a heartbeat file")
        out = []
        now_w = time.time()
        for i in range(slots):
            off = _HEADER.size + i * SLOT_SIZE
            if off + _RECORD.size > len(buf):
                break
            seq, mono, wall, pid, phase = _RECORD.unpack_from(buf, off)
            if seq == 0:
                continue
            out.append(
                {
                    "seq": seq,
                    "phase": phase.split(b"\x00", 1)[0].decode(errors="replace"),
                    "wall_ts": round(wall, 6),
                    "age_s": round(now_w - wall, 3),
                    "pid": pid,
                }
            )
        out.sort(key=lambda r: r["seq"])
        if limit is not None and limit >= 0:
            out = out[-limit:] if limit else []
        return out


# -- module-global writer (the hot-path surface) ------------------------------

_HB: Optional[Heartbeat] = None
_HB_LOCK = threading.Lock()
_OUT_DIR: Optional[str] = None
_CAPTURE_SEQ = 0

# Fallback runtime dir for captures when no directory was ever configured:
# never litter the process cwd/repo root with FORENSICS_*.json (ISSUE 8
# satellite; [instrumentation] forensics_dir defaults here too).
DEFAULT_DIR = os.path.join(".", "forensics")

_HB_NAME_RE = None  # compiled lazily (keep the import-time path tiny)


def sweep_stale_heartbeats(directory: str) -> List[str]:
    """Remove heartbeat_<pid>.bin files whose pid is DEAD (and not ours).
    Returns the removed paths. A live ring is never touched — a concurrent
    node in the same dir keeps its file; only the corpses of crashed or
    SIGKILLed runs are swept (they accumulate one per pid otherwise)."""
    import re

    global _HB_NAME_RE
    if _HB_NAME_RE is None:
        _HB_NAME_RE = re.compile(r"^heartbeat_(\d+)\.bin$")
    removed: List[str] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return removed
    for name in names:
        m = _HB_NAME_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)
            continue  # alive: leave its ring alone
        except ProcessLookupError:
            pass  # dead: sweep
        except OSError:
            continue  # exists but not ours to signal: leave it
        try:
            os.unlink(os.path.join(directory, name))
            removed.append(os.path.join(directory, name))
        except OSError:
            pass
    return removed


def configure(directory: Optional[str], slots: int = DEFAULT_SLOTS) -> Optional[str]:
    """Enable (or with None disable) the process heartbeat under `directory`.
    Returns the heartbeat file path. Also sets the default FORENSICS_*.json
    output directory and sweeps heartbeat rings left behind by dead pids.
    Wired from `[instrumentation] forensics_dir` (node/node.py, default
    ./forensics), the TMTPU_FORENSICS_DIR env default, and bench.py's
    scenario children."""
    global _HB, _OUT_DIR
    with _HB_LOCK:
        if _HB is not None:
            _HB.close()
            _HB = None
        if not directory:
            _OUT_DIR = None
            return None
        _OUT_DIR = directory
        _HB = Heartbeat(
            os.path.join(directory, f"heartbeat_{os.getpid()}.bin"), slots
        )
        path = _HB.path  # read under the lock: a concurrent configure(None)
    sweep_stale_heartbeats(directory)  # may clear _HB before we return
    return path


def enabled() -> bool:
    return _HB is not None


def heartbeat_path() -> Optional[str]:
    hb = _HB
    return hb.path if hb is not None else None


def beat(phase: str) -> None:
    """Stamp a phase. ONE None check when forensics is not configured — safe
    on the device hot path (crypto/batch._device_fault)."""
    hb = _HB
    if hb is not None:
        hb.beat(phase)


def _heartbeat_tail(limit: int = 16) -> List[dict]:
    hb = _HB
    if hb is None:
        return []
    try:
        return Heartbeat.read(hb.path, limit)
    except Exception:
        return []


# -- capture ------------------------------------------------------------------


def _thread_stacks() -> str:
    """Every thread's stack. faulthandler first (it walks the interpreter
    state in C, so it renders a thread wedged inside a C call); pure-Python
    fallback if faulthandler can't write."""
    import tempfile

    try:
        import faulthandler

        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception:
        pass
    import traceback

    chunks = []
    for tid, frame in sys._current_frames().items():
        chunks.append(f"Thread {tid}:\n" + "".join(traceback.format_stack(frame)))
    return "\n".join(chunks)


def _probe_jax_devices(timeout_s: float = 2.0) -> dict:
    """`jax.devices()` health, probed from a side thread with a deadline —
    in the observed failure mode (BENCH_r05) the call itself never returns,
    and that non-return is exactly what the forensics file should say."""
    result: Dict[str, Any] = {}

    def _probe():
        try:
            import jax

            result["devices"] = [str(d) for d in jax.devices()]
            result["backend"] = jax.default_backend()
        except Exception as e:  # no jax / broken backend: still a diagnosis
            result["error"] = repr(e)

    t = threading.Thread(target=_probe, name="forensics-jax-probe", daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return {"error": f"jax.devices() did not return within {timeout_s:g}s"}
    return result


def capture(
    reason: str,
    *,
    kind: str = "manual",
    wedged_phase: Optional[str] = None,
    extra: Optional[dict] = None,
    out_dir: Optional[str] = None,
    probe_devices: bool = True,
) -> str:
    """Assemble and write a FORENSICS_<stamp>_<pid>.json; returns its path.

    Never raises past its own boundary and never depends on the wedged
    thread: every section degrades to an error string independently. `kind`
    labels the metrics counter (watchdog / signal / timeout / manual)."""
    ts = time.time()
    tail = _heartbeat_tail()
    doc: Dict[str, Any] = {
        "reason": reason,
        "kind": kind,
        "ts": round(ts, 3),
        "pid": os.getpid(),
        "argv": sys.argv,
        "wedged_phase": wedged_phase
        or (tail[-1]["phase"] if tail else None),
        "heartbeat": tail,
        "heartbeat_file": heartbeat_path(),
    }
    try:
        from tendermint_tpu.ops.cache_hardening import machine_fingerprint

        doc["machine_fingerprint"] = machine_fingerprint()
    except Exception as e:
        doc["machine_fingerprint"] = f"error: {e!r}"
    try:
        doc["threads"] = _thread_stacks()
    except Exception as e:
        doc["threads"] = f"error: {e!r}"
    try:
        from tendermint_tpu.crypto.batch import BREAKER, LAST_FLUSH_DETAIL

        doc["breaker"] = BREAKER.snapshot()
        doc["last_flush_detail"] = dict(LAST_FLUSH_DETAIL)
    except Exception as e:
        doc["breaker"] = f"error: {e!r}"
    try:
        from tendermint_tpu.libs import trace as _trace

        doc["device_health"] = _trace.device_health()
    except Exception as e:
        doc["device_health"] = f"error: {e!r}"
    doc["jax"] = _probe_jax_devices() if probe_devices else {"skipped": True}
    if extra:
        doc["extra"] = extra

    d = out_dir or _OUT_DIR or DEFAULT_DIR
    stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime(ts))
    with _HB_LOCK:
        global _CAPTURE_SEQ
        _CAPTURE_SEQ += 1
        seq = _CAPTURE_SEQ
    # pid + per-process seq: two captures in the same second (watchdog +
    # signal racing, say) must not overwrite each other
    path = os.path.join(d, f"FORENSICS_{stamp}_{os.getpid()}_{seq}.json")
    try:
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=repr)
        os.replace(tmp, path)
    except Exception:
        # last resort: the diagnosis still reaches the scenario log
        print(json.dumps(doc, default=repr), file=sys.stderr, flush=True)
    try:
        from tendermint_tpu.libs import metrics as _metrics

        _metrics.observatory_metrics().forensics_captures.labels(kind).inc()
    except Exception:
        pass
    try:
        from tendermint_tpu.libs.trace import tracer

        if tracer.enabled:
            tracer.event(
                "forensics.capture",
                reason=reason,
                kind=kind,
                wedged_phase=doc["wedged_phase"],
                path=path,
            )
    except Exception:
        pass
    return path


def find_captures(directory: str, since_ts: float = 0.0) -> List[str]:
    """FORENSICS_*.json files under `directory` newer than `since_ts`,
    oldest first (the bench parent attaches these to a killed scenario's
    error report)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for n in sorted(names):
        if n.startswith("FORENSICS_") and n.endswith(".json"):
            p = os.path.join(directory, n)
            try:
                if os.path.getmtime(p) >= since_ts:
                    out.append(p)
            except OSError:
                pass
    return out


class Watchdog:
    """Fire `capture()` if not cancelled within `timeout_s`.

    A daemon THREAD, deliberately not a signal: the observed hangs block the
    main thread inside C without servicing SIGALRM, while a side thread
    still runs (the tunnel waits release the GIL). Arm it around anything
    that can wedge — bench.py arms one per scenario child just inside the
    parent's hard process-group deadline."""

    def __init__(
        self,
        timeout_s: float,
        reason: str,
        *,
        out_dir: Optional[str] = None,
        extra: Optional[dict] = None,
        on_fire=None,
    ):
        self.timeout_s = float(timeout_s)
        self.reason = reason
        self.out_dir = out_dir
        self.extra = extra
        self.on_fire = on_fire
        self.fired = False
        self.capture_path: Optional[str] = None
        self._cancel = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        t = threading.Thread(
            target=self._run, name="forensics-watchdog", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def _run(self) -> None:
        if self._cancel.wait(self.timeout_s):
            return
        self.fired = True
        try:
            self.capture_path = capture(
                self.reason,
                kind="watchdog",
                out_dir=self.out_dir,
                extra=self.extra,
            )
        finally:
            if self.on_fire is not None:
                try:
                    self.on_fire(self)
                except Exception:
                    pass

    def cancel(self) -> None:
        self._cancel.set()

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.cancel()


def install_signal_handler(signum: Optional[int] = None) -> bool:
    """Dump forensics on demand from OUTSIDE the process (default SIGUSR1):
    the bench parent signals a timed-out child and waits briefly for the
    FORENSICS file before the SIGKILL. Best-effort — a main thread wedged in
    C that never re-enters the interpreter cannot run Python signal
    handlers; the Watchdog covers that case."""
    import signal

    if signum is None:
        signum = getattr(signal, "SIGUSR1", None)
        if signum is None:  # pragma: no cover - non-POSIX
            return False

    def _handler(_sig, _frame):
        # no device probe here: the parent SIGKILLs a few seconds after the
        # signal, and the probe's join window would eat the whole grace
        # period exactly when the device is wedged (the watchdog path, with
        # no kill racing it, still probes)
        capture("signal-requested dump", kind="signal", probe_devices=False)

    try:
        signal.signal(signum, _handler)
        return True
    except (ValueError, OSError):  # not the main thread, or unsupported
        return False


# Env default, mirroring TMTPU_TRACE: a process started with
# TMTPU_FORENSICS_DIR set heartbeats (and writes captures) there without any
# explicit configure() call.
_env_dir = os.environ.get("TMTPU_FORENSICS_DIR")
if _env_dir:
    try:
        configure(_env_dir)
    except Exception:  # never fail an import over forensics plumbing
        pass
