"""Transaction & request observatory (ISSUE 10): libs/txtrace.py's journey
ring, the mempool/consensus/deliver hooks, the tx_status / /debug/tx_trace /
/debug/rpc serving surface, per-method RPC telemetry, and the light
service's per-request stage spans.

The acceptance proofs live in test_node_tx_status_waterfall_e2e: one
broadcast_tx_sync through a real node yields a complete monotonic
received→checked→admitted→proposed→committed→delivered waterfall, the new
tendermint_tx_*/tendermint_rpc_request_* series are live on /metrics, and
the tx_commit_latency / rpc_request_p99 SLO budgets are live on /debug/slo.
Pure-host tests — no crypto wheel, no TPU, no p2p listener."""

import asyncio
import os
import time
from types import SimpleNamespace

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.txtrace import STAGES, StageStats, TxTracker
from tendermint_tpu.mempool.mempool import Mempool


@pytest.fixture(autouse=True)
def _tracing_on():
    """The tracker follows the process-global tracer flag; pin it on (and
    restore) so a prior test's configure() can't flake these."""
    prev = trace.tracer.enabled
    trace.tracer.enabled = True
    yield
    trace.tracer.enabled = prev


def h(b: bytes) -> bytes:
    return tmhash.sum256(b)


# ---------------------------------------------------------------------------
# the tracker itself


def test_full_journey_waterfall_monotonic_with_durations():
    tt = TxTracker(max_txs=64)
    key = h(b"tx-1")
    tt.record(key, "received", via="rpc")
    tt.record(key, "checked", code=0, priority=3)
    tt.record(key, "admitted", priority=3)
    tt.record(key, "first_gossiped", peer="peer0")
    tt.record(key, "proposed", height=5, round=0, index=0)
    tt.record(key, "committed", height=5, round=0, index=0)
    tt.record(key, "delivered", height=5, index=0, code=0)

    wf = tt.waterfall(key)
    assert wf is not None
    assert [s["stage"] for s in wf["stages"]] == list(STAGES)
    assert wf["terminal"] == "delivered" and wf["complete"] is True
    # monotonic: offsets never decrease, durations never negative
    offsets = [s["offset_ms"] for s in wf["stages"]]
    assert offsets == sorted(offsets) and offsets[0] == 0.0
    assert all(s["dur_ms"] >= 0.0 for s in wf["stages"])
    assert wf["total_ms"] >= offsets[-1] - 1e-9
    # attrs ride the stage entries
    by_stage = {s["stage"]: s for s in wf["stages"]}
    assert by_stage["received"]["via"] == "rpc"
    assert by_stage["checked"]["code"] == 0
    assert by_stage["committed"]["height"] == 5
    assert by_stage["delivered"]["code"] == 0
    # terminal + stage accounting
    st = tt.stats()
    assert st["terminals"] == {"delivered": 1}
    assert st["stage_counts"]["received"] == 1
    assert set(st["stage_percentiles"]) == set(STAGES)


def test_non_ingress_stages_need_a_received_journey():
    """Only txs first seen at ingress are tracked: a blocksync replay's
    foreign commits must not flush the ring."""
    tt = TxTracker(max_txs=64)
    assert tt.record(h(b"foreign"), "committed", height=9, round=0) is False
    assert tt.waterfall(h(b"foreign")) is None
    assert tt.stats()["tracked"] == 0


def test_disabled_tracer_records_nothing():
    tt = TxTracker(max_txs=64)
    trace.tracer.enabled = False
    assert tt.enabled is False
    assert tt.record(h(b"x"), "received", via="rpc") is False
    trace.tracer.enabled = True
    assert tt.stats()["tracked"] == 0


def test_duplicate_stage_first_wins_and_terminal_reset_reenters():
    tt = TxTracker(max_txs=64)
    key = h(b"retry")
    tt.record(key, "received", via="gossip")
    assert tt.record(key, "received", via="rpc") is False  # dup ignored
    tt.record(key, "rejected", reason="full")
    assert tt.waterfall(key)["terminal"] == "rejected"
    # a terminal ENDS the journey: later non-ingress stages (e.g. this tx
    # committed via a peer's block after local eviction) never overwrite
    # the terminal or double-count the outcome counters
    assert tt.record(key, "committed", height=9, round=0) is False
    assert tt.record(key, "delivered", height=9, code=0) is False
    assert tt.waterfall(key)["terminal"] == "rejected"
    assert tt.stats()["terminals"].get("delivered") is None
    # a resubmission after the terminal starts a FRESH journey
    assert tt.record(key, "received", via="rpc") is True
    wf = tt.waterfall(key)
    assert wf["terminal"] is None
    assert [s["stage"] for s in wf["stages"]] == ["received"]
    assert wf["stages"][0]["via"] == "rpc"
    # reason-qualified terminal accounting survived the reset
    assert tt.stats()["terminals"]["rejected:full"] == 1


def test_ring_bounded_oldest_evicted_under_10k_flood():
    cap = 256
    tt = TxTracker(max_txs=cap, metrics=M.TxLifecycleMetrics(M.Registry()))
    n = 10_000
    for i in range(n):
        tt.record(h(b"flood-%d" % i), "received", via="rpc")
    st = tt.stats()
    assert st["tracked"] == cap
    assert st["ring_evictions"] == n - cap
    # oldest gone, newest retained
    assert tt.waterfall(h(b"flood-0")) is None
    assert tt.waterfall(h(b"flood-%d" % (n - 1))) is not None
    # a survivor's journey still extends normally
    assert tt.record(h(b"flood-%d" % (n - 1)), "checked", code=0, priority=0)


def test_stage_stats_percentiles_bounded():
    ss = StageStats(maxlen=16)
    for i in range(100):
        ss.observe("s", i / 1000.0)
    p = ss.percentiles()["s"]
    assert p["count"] == 100  # lifetime count
    assert p["max_ms"] == pytest.approx(99.0)
    # percentiles cover only the newest maxlen samples (84..99 ms)
    assert p["p50_ms"] >= 84.0


# ---------------------------------------------------------------------------
# mempool admission hooks (terminal states)


class PrioApp(ABCIClient):
    def check_tx(self, req):
        tx = req.tx
        prio = 0
        if tx.startswith(b"p") and b":" in tx:
            prio = int(tx[1 : tx.index(b":")])
        code = abci.CODE_TYPE_OK if not tx.startswith(b"bad") else 1
        return abci.ResponseCheckTx(code=code, priority=prio)


def make_pool(**kw):
    tt = TxTracker(max_txs=512)
    defaults = dict(max_txs=3, tx_tracker=tt)
    defaults.update(kw)
    return Mempool(PrioApp(), **defaults), tt


def test_mempool_admitted_checked_attrs():
    mp, tt = make_pool()
    mp.check_tx(b"p7:a")
    wf = tt.waterfall(h(b"p7:a"))
    stages = [s["stage"] for s in wf["stages"]]
    assert stages == ["received", "checked", "admitted"]
    by = {s["stage"]: s for s in wf["stages"]}
    assert by["received"]["via"] == "rpc"
    assert by["checked"]["priority"] == 7
    assert by["admitted"]["priority"] == 7


def test_mempool_eviction_records_terminal():
    mp, tt = make_pool()
    for tx in (b"p5:a", b"p1:b", b"p3:c"):
        mp.check_tx(tx)
    mp.check_tx(b"p4:d")  # evicts the p1 resident
    wf = tt.waterfall(h(b"p1:b"))
    assert wf["terminal"] == "evicted"
    assert tt.stats()["terminals"]["evicted"] == 1


def test_mempool_ttl_expiry_records_terminal():
    mp, tt = make_pool(ttl_num_blocks=1)
    mp.check_tx(b"p0:old")
    mp.update(2, [], [])  # height jump past the TTL purges it
    assert tt.waterfall(h(b"p0:old"))["terminal"] == "expired"
    assert tt.stats()["terminals"]["expired"] == 1


def test_mempool_quota_and_refusals_record_reasons():
    mp, tt = make_pool(max_txs_per_sender=1)
    mp.check_tx(b"p0:s1", sender="peerA")
    mp.check_tx(b"p0:s2", sender="peerA")  # over quota, silent drop
    assert tt.waterfall(h(b"p0:s2"))["terminal"] == "rejected"
    assert tt.stats()["terminals"]["rejected:quota"] == 1
    # gossip receipt is attributed to its channel
    assert tt.waterfall(h(b"p0:s1"))["stages"][0]["via"] == "gossip"

    # too_large (local submission raises; the journey still records)
    mp2, tt2 = make_pool(max_tx_bytes=4)
    with pytest.raises(Exception):
        mp2.check_tx(b"way-too-large")
    assert tt2.stats()["terminals"]["rejected:too_large"] == 1

    # CheckTx failure
    mp3, tt3 = make_pool()
    mp3.check_tx(b"bad-tx")
    wf = tt3.waterfall(h(b"bad-tx"))
    assert wf["terminal"] == "rejected"
    assert tt3.stats()["terminals"]["rejected:checktx"] == 1
    assert {s["stage"] for s in wf["stages"]} == {"received", "checked", "rejected"}


def test_mempool_full_no_eviction_records_full_reason():
    mp, tt = make_pool(eviction=False)
    for tx in (b"p0:a", b"p0:b", b"p0:c"):
        mp.check_tx(tx)
    mp.check_tx(b"p0:d", sender="peerB")  # silent gossip drop
    assert tt.stats()["terminals"]["rejected:full"] == 1


def test_resident_duplicate_submission_never_poisons_live_journey():
    """A client retrying broadcast of a PENDING tx (the standard polling/
    retry pattern) must not terminal the live journey as rejected:cache —
    the tx is still on its way to a block."""
    mp, tt = make_pool(max_txs=16)
    mp.check_tx(b"p0:live")
    key = h(b"p0:live")
    assert tt.waterfall(key)["terminal"] is None
    with pytest.raises(Exception):  # the submission IS refused...
        mp.check_tx(b"p0:live")
    wf = tt.waterfall(key)
    assert wf["terminal"] is None  # ...but the journey stays live
    assert tt.stats()["terminals"].get("rejected:cache") is None
    # and it still extends to commit normally
    assert tt.record(key, "proposed", height=2, round=0, index=0) is True


def test_delivered_journey_survives_rebroadcast():
    """Re-broadcasting a COMMITTED tx (cache blocks the replay) must keep
    the delivered waterfall — tx_status answers 'delivered at height H',
    never 'rejected:cache'."""
    tt = TxTracker(max_txs=64)
    key = h(b"done")
    tt.record(key, "received", via="rpc")
    tt.record(key, "delivered", height=3, code=0)
    # the re-broadcast's ingress stamp does NOT reset a delivered journey
    assert tt.record(key, "received", via="rpc") is False
    # and the cache reject can't overwrite the terminal either
    assert tt.record(key, "rejected", reason="cache") is False
    wf = tt.waterfall(key)
    assert wf["terminal"] == "delivered" and wf["complete"] is True


def test_recheck_failure_records_terminal():
    """A tx dropped on post-commit recheck (app flipped to non-OK) must not
    read 'admitted' forever."""

    class FlipApp(PrioApp):
        def __init__(self):
            self.flip = False

        def check_tx(self, req):
            if self.flip and req.type == abci.CHECK_TX_TYPE_RECHECK:
                return abci.ResponseCheckTx(code=5)
            return super().check_tx(req)

    tt = TxTracker(max_txs=64)
    app = FlipApp()
    mp = Mempool(app, max_txs=16, tx_tracker=tt)
    mp.check_tx(b"p0:re")
    app.flip = True
    mp.update(1, [], [])  # recheck now fails -> tx dropped
    wf = tt.waterfall(h(b"p0:re"))
    assert wf["terminal"] == "rejected"
    assert tt.stats()["terminals"]["rejected:recheck"] == 1


# ---------------------------------------------------------------------------
# gossip fan-out hook (first_gossiped)


def test_reactor_first_gossiped_on_successful_send():
    import contextlib

    from tendermint_tpu.mempool.reactor import MempoolReactor

    mp, tt = make_pool(max_txs=16)
    mp.check_tx(b"p0:gg")
    reactor = MempoolReactor(mp)

    class StubPeer:
        id = "stub-peer-000000"
        sent = 0

        async def send(self, ch, data):
            StubPeer.sent += 1
            return True

    async def drive():
        # the walk loops forever once everything is sent; bound it
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(
                reactor._broadcast_tx_routine(StubPeer()), timeout=0.2
            )

    asyncio.run(drive())
    assert StubPeer.sent == 1
    wf = tt.waterfall(h(b"p0:gg"))
    assert wf["stages"][-1]["stage"] == "first_gossiped"
    assert wf["stages"][-1]["peer"] == "stub-peer-"
    # a second fan-out (another peer) never re-stamps the stage
    key = h(b"p0:gg")
    assert tt.record(key, "first_gossiped", peer="other-peer") is False


# ---------------------------------------------------------------------------
# per-method RPC telemetry (_dispatch + slow ring + /debug/rpc)


def _make_rpc_server():
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.rpc.server import RPCServer

    cfg = test_config()
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    nm = M.NodeMetrics()
    node = SimpleNamespace(config=cfg, metrics=nm, slo=None, tx_tracker=None)
    return RPCServer(node), nm


def test_dispatch_observes_duration_outcome_and_folds_unknown_methods():
    srv, nm = _make_rpc_server()

    async def go():
        # ok
        await srv._dispatch("health", srv._routes["health"], {})

        # error
        async def boom(params):
            raise RuntimeError("kaboom")

        with pytest.raises(RuntimeError):
            await srv._dispatch("tx", boom, {})
        # shed: gate full for a sheddable method
        from tendermint_tpu.rpc.server import RPCShedError

        srv.gate.max_inflight = 1
        srv.gate.inflight = 1
        with pytest.raises(RPCShedError):
            await srv._dispatch("broadcast_tx_sync", boom, {})
        srv.gate.inflight = 0
        # unknown method name folds into _other (bounded cardinality)
        async def ok(params):
            return {}

        await srv._dispatch("made_up_method_xyz", ok, {})

    asyncio.run(go())
    counts = {k: v for k, v in nm.rpc.requests._values.items()}
    assert counts[("health", "ok")] == 1
    assert counts[("tx", "error")] == 1
    assert counts[("broadcast_tx_sync", "shed")] == 1
    assert counts[("_other", "ok")] == 1
    # histogram series exist per method label, bounded to the route table
    assert ("health",) in nm.rpc.request_duration._totals
    assert ("_other",) in nm.rpc.request_duration._totals
    assert not any(lbl == ("made_up_method_xyz",) for lbl in nm.rpc.request_duration._totals)
    # the /debug/rpc aggregate mirrors it
    doc = asyncio.run(srv._debug_rpc({}))
    assert doc["methods"]["health"]["ok"] == 1
    assert doc["methods"]["tx"]["error"] == 1
    assert doc["gate"]["shed_total"] == 1


def test_slow_ring_keeps_top_n_by_duration():
    from tendermint_tpu.rpc.server import SlowRequestRing

    ring = SlowRequestRing(cap=3)
    for ms in (5, 1, 9, 3, 7, 2):
        ring.offer(ms / 1e3, {"method": "m", "duration_ms": float(ms)})
    snap = ring.snapshot()
    assert [e["duration_ms"] for e in snap] == [9.0, 7.0, 5.0]


def test_dispatch_feeds_slow_ring_with_annotations():
    srv, _ = _make_rpc_server()

    async def slowpoke(params):
        await asyncio.sleep(0.005)
        return {}

    asyncio.run(srv._dispatch("abci_query", slowpoke, {}))
    doc = asyncio.run(srv._debug_rpc({}))
    assert doc["slow_requests"], "a 5ms request must enter the slow ring"
    e = doc["slow_requests"][0]
    assert e["method"] == "abci_query" and e["outcome"] == "ok"
    assert e["duration_ms"] >= 5.0
    assert {"inflight_at_dispatch", "shed_writes", "shed_reads", "error"} <= set(e)


def test_rpc_request_p99_slo_fed_per_request():
    from tendermint_tpu.config.config import SLOConfig
    from tendermint_tpu.libs.slo import SLOEngine

    srv, _ = _make_rpc_server()
    srv.node.slo = SLOEngine(SLOConfig())
    asyncio.run(srv._dispatch("health", srv._routes["health"], {}))
    snap = srv.node.slo.snapshot()
    assert snap["objectives"]["rpc_request_p99"]["observations"] == 1


# ---------------------------------------------------------------------------
# node e2e: the acceptance proof


def test_node_tx_status_waterfall_e2e(tmp_path, monkeypatch):
    """broadcast_tx_sync → commit on a real single-validator node yields a
    complete monotonic waterfall covering every single-node stage
    (received→checked→admitted→proposed→committed→delivered; first_gossiped
    needs a peer and is legitimately absent here), the new series are live
    on /metrics, and both new SLO budgets are live on /debug/slo."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import LocalClient
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    monkeypatch.chdir(tmp_path)
    cfg = test_config()
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = ""
    cfg.root_dir = ""
    priv = FilePV(gen_ed25519(b"\x10" * 32))
    gen = GenesisDoc(
        chain_id="txtrace-e2e",
        validators=[GenesisValidator(priv.get_pub_key(), 10)],
    )
    node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
    assert node.tx_tracker is not None

    async def run():
        await node.start()
        client = LocalClient(node)
        try:
            await node.wait_for_height(1, timeout=30)
            res = await client.call("broadcast_tx_sync", tx="0x" + b"k1=v1".hex())
            assert res["code"] == 0
            tx_hash = res["hash"]
            deadline = time.monotonic() + 30
            wf = None
            while time.monotonic() < deadline:
                try:
                    wf = await client.call("tx_status", hash=tx_hash)
                except Exception:
                    wf = None
                # wait for the async indexer too so `indexed` is attached
                if (
                    wf is not None
                    and wf.get("terminal") == "delivered"
                    and "indexed" in wf
                ):
                    break
                await asyncio.sleep(0.05)
            assert wf is not None and wf["terminal"] == "delivered", wf
            stages = [s["stage"] for s in wf["stages"]]
            assert stages == [
                "received", "checked", "admitted", "proposed",
                "committed", "delivered",
            ], stages
            offsets = [s["offset_ms"] for s in wf["stages"]]
            assert offsets == sorted(offsets)
            assert all(s["dur_ms"] >= 0.0 for s in wf["stages"])
            assert wf["complete"] is True
            by = {s["stage"]: s for s in wf["stages"]}
            assert by["received"]["via"] == "rpc"
            assert by["committed"]["height"] >= 1
            assert by["delivered"]["code"] == 0
            assert wf["indexed"]["code"] == 0

            # unknown hash: the routine polling answer, not a 500
            nf = await client.call("tx_status", hash="ab" * 32)
            assert nf["found"] is False and "reason" in nf
            assert wf["found"] is True

            # the hash-less debug doc: ring stats + stage percentiles
            st = await client.call("debug_tx_trace")
            assert st["tracked"] >= 1
            assert st["terminals"].get("delivered", 0) >= 1
            assert "committed" in st["stage_percentiles"]

            # /debug/rpc attributes the requests this test just made
            rpc_doc = await client.call("debug_rpc")
            assert rpc_doc["methods"]["broadcast_tx_sync"]["count"] == 1
            assert rpc_doc["methods"]["tx_status"]["count"] >= 1

            # both new SLO budgets live on /debug/slo; tx_commit_latency has
            # at least this tx's observation and holds its budget
            slo_doc = await client.call("debug_slo")
            assert {"tx_commit_latency", "rpc_request_p99"} <= set(
                slo_doc["objectives"]
            )
            tcl = slo_doc["objectives"]["tx_commit_latency"]
            assert tcl["observations"] >= 1 and tcl["breaches"] == 0

            # the new series are on the /metrics exposition
            text = node.metrics.expose()
            assert 'tendermint_tx_stage_seconds_bucket{stage="committed"' in text
            assert 'tendermint_tx_terminal_total{outcome="delivered"} ' in text
            assert (
                'tendermint_rpc_request_duration_seconds_bucket'
                '{method="broadcast_tx_sync"' in text
            )
            assert 'tendermint_rpc_requests_total{method="tx_status", outcome="ok"}' in text

            # the debug index advertises the new endpoints
            idx = await client.call("debug_index")
            paths = {e["path"] for e in idx["endpoints"]}
            assert {"/debug/tx_trace", "/debug/rpc"} <= paths
        finally:
            await node.stop()

    asyncio.run(run())


def test_node_tx_status_unknown_hash_and_disabled_tracker(tmp_path, monkeypatch):
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import LocalClient
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    monkeypatch.chdir(tmp_path)
    cfg = test_config()
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = ""
    cfg.root_dir = ""
    cfg.instrumentation.txtrace_enabled = False
    priv = FilePV(gen_ed25519(b"\x11" * 32))
    gen = GenesisDoc(
        chain_id="txtrace-off",
        validators=[GenesisValidator(priv.get_pub_key(), 10)],
    )
    node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
    assert node.tx_tracker is None

    async def run():
        await node.start()
        client = LocalClient(node)
        try:
            # disabled tracker: structured degrade on BOTH routes, not a
            # -32603 + stack trace per routine poll
            doc = await client.call("debug_tx_trace")
            assert doc == {"enabled": False}
            st = await client.call("tx_status", hash="ab" * 32)
            assert st["enabled"] is False and st["found"] is False
        finally:
            await node.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# light service per-request stage spans


def test_light_service_stage_percentiles():
    import test_light as lt

    from tendermint_tpu.config.config import LightServiceConfig
    from tendermint_tpu.light.provider import MockProvider
    from tendermint_tpu.light.service import LightService

    blocks = lt.make_chain(8)
    svc = LightService(
        lt.CHAIN_ID,
        MockProvider(lt.CHAIN_ID, blocks),
        LightServiceConfig(coalesce_window=0.01, max_heights_per_flush=16),
        now_ns=lambda: lt.NOW,
    )

    async def go():
        await asyncio.gather(*(svc.verify_height(hh) for hh in (3, 4, 5, 6)))
        await svc.verify_height(3)  # a pure cache hit

    try:
        asyncio.run(go())
        sp = svc.status()["stage_percentiles"]
        # every request paid a cache probe; misses paid the window + the
        # shared flush; at least one window fired
        assert sp["cache_probe"]["count"] >= 5
        assert sp["coalesce_wait"]["count"] >= 1
        assert sp["flush_wall"]["count"] >= 1
        assert sp["admission"]["count"] >= 1
        assert sp["provider_fetch"]["count"] >= 1
        for v in sp.values():
            assert v["p50_ms"] >= 0.0 and v["p99_ms"] >= v["p50_ms"] - 1e-9
        # the same doc rides GET /debug/light's stats()
        assert "stage_percentiles" in svc.stats()
    finally:
        svc.close()
